#!/usr/bin/env python
"""Config #4: BERT-large pretraining (masked-LM objective) on a TPU slice.

dp×fsdp mesh: batch sharded over both axes, params sharded over fsdp
(HBM capacity), flash-attention pallas kernel on the MXU hot path
(ops/flash_attention.py). The reference runs the equivalent via
TPUStrategy inside a TF container (SURVEY §2.10 row 'TPU-native
equivalents'); here the framework owns the math end to end.
"""
import argparse
import sys

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.transformer import Transformer, bert_large, tiny
from tf_operator_tpu.parallel.mesh import make_mesh, local_mesh_axes
from tf_operator_tpu.parallel.tp import state_sharding
from tf_operator_tpu.runtime import bootstrap
from tf_operator_tpu.runtime.loop import PreemptionGuard, run_training
from tf_operator_tpu.runtime.profiler import Profiler
from tf_operator_tpu.runtime.train import Checkpointer, TrainState


def mlm_batches(batch: int, seq_len: int, vocab: int, seed: int):
    """Synthetic masked-LM batches: (tokens, labels); label -100 = unmasked."""
    key = jax.random.PRNGKey(seed)
    while True:
        key, k1, k2 = jax.random.split(key, 3)
        tokens = jax.random.randint(k1, (batch, seq_len), 0, vocab)
        mask = jax.random.bernoulli(k2, 0.15, (batch, seq_len))
        labels = jnp.where(mask, tokens, -100)
        yield (jnp.where(mask, 103, tokens), labels)  # 103 = [MASK]


def make_mlm_step(model):
    def step(state: TrainState, tokens, labels):
        def loss_fn(params):
            logits = model.apply({"params": params}, tokens, train=True)
            valid = labels >= 0
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), jnp.maximum(labels, 0)
            )
            return (ce * valid).sum() / jnp.maximum(valid.sum(), 1)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), {"loss": loss}

    return jax.jit(step, donate_argnums=(0,))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10000)
    ap.add_argument("--per-host-batch", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--smoke", action="store_true", help="tiny model, CPU ok")
    args = ap.parse_args(argv)

    info = bootstrap.initialize()
    if args.smoke:
        cfg = tiny()
    else:
        # pallas flash attention on the MXU hot path (1.45-2.2x the einsum
        # path on a v5e chip — BASELINE.md); interpret-mode off-TPU
        from tf_operator_tpu.ops.flash_attention import flash_attention

        cfg = bert_large(remat=True, attention_fn=flash_attention)
    seq_len = min(args.seq_len, cfg.max_len)
    mesh = make_mesh(axes=local_mesh_axes(jax.device_count()))
    print(f"host {info.process_id}/{info.num_processes}, mesh {dict(mesh.shape)}")

    model = Transformer(cfg)
    tx = optax.adamw(1e-4, weight_decay=0.01)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((args.per_host_batch, seq_len), jnp.int32)
    params = model.init(rng, sample, train=False)["params"]
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=tx.init(params), batch_stats={}, tx=tx,
    )
    # shard params/opt-state over the mesh (tp + fsdp overlay)
    state = jax.device_put(state, state_sharding(state, mesh))

    res = run_training(
        state,
        make_mlm_step(model),
        mlm_batches(args.per_host_batch, seq_len, cfg.vocab_size,
                    seed=info.process_id),
        num_steps=args.steps,
        checkpointer=(
            Checkpointer(args.ckpt_dir, async_save=True)
            if args.ckpt_dir else None
        ),
        profiler=Profiler(batch_size=args.per_host_batch * jax.process_count()),
        guard=PreemptionGuard(),
        metrics_sink=print,
    )
    print(f"done: steps={res.steps_run} loss={res.last_metrics.get('loss')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
