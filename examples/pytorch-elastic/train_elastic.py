#!/usr/bin/env python
"""Elastic-worker training entry: reads the torchrun rendezvous contract
the operator injects (PET_* — docs/env_contract.md) and launches the real
`torchrun` when available, else demonstrates the env round-trip.

In production the container command would simply be

    torchrun --nnodes=$PET_NNODES --nproc-per-node=$PET_NPROC_PER_NODE \
             --rdzv-backend=$PET_RDZV_BACKEND --rdzv-endpoint=$PET_RDZV_ENDPOINT \
             --rdzv-id=$PET_RDZV_ID train.py

torchrun reads exactly these variables from the environment, so the
operator-injected values need no flag plumbing at all — this script just
makes the contract visible and testable without torch installed.
"""
import os
import shutil
import subprocess
import sys


def main() -> int:
    contract = {
        k: os.environ.get(k, "")
        for k in (
            "PET_RDZV_BACKEND",
            "PET_RDZV_ENDPOINT",
            "PET_RDZV_ID",
            "PET_NNODES",
            "PET_NPROC_PER_NODE",
            "PET_MAX_RESTARTS",
        )
    }
    missing = [k for k in ("PET_RDZV_ENDPOINT", "PET_NNODES") if not contract[k]]
    if missing:
        print(f"not an elastic pod: missing {missing}", file=sys.stderr)
        return 1
    for k, v in contract.items():
        if v:
            print(f"{k}={v}", flush=True)

    if shutil.which("torchrun") and os.environ.get("RUN_TORCH", "") == "1":
        return subprocess.call(
            ["torchrun", "--no-python", "python", "-c", "print('trained')"]
        )
    print("elastic contract ok", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
