#!/usr/bin/env python
"""[+] LLaMA-class GQA decoder pretraining on a TPU slice.

Beyond the reference ladder (BASELINE.md tops out at T5): the modern
decoder recipe on the same runtime seams as train_t5.py — dp×fsdp×tp
mesh, GQA-native flash attention (compact kv heads, models/llama.py),
optional sequence-parallel ring for long context (--ring: the compact
kv shard is what ppermutes, ops/ring_flash.py), blocked large-vocab CE
over the tied embedding, adafactor + remat, checkpoint on interval AND
on SIGTERM for gang preemption recovery.
"""
import argparse
import sys

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.llama import (
    Llama, llama3_8b, llama31_8b, mistral_7b, mixtral_8x7b, tiny,
)
from tf_operator_tpu.models.transformer import lm_loss
from tf_operator_tpu.ops.blocked_ce import lm_blocked_loss
from tf_operator_tpu.parallel.mesh import make_mesh, local_mesh_axes
from tf_operator_tpu.parallel.tp import state_sharding
from tf_operator_tpu.runtime import bootstrap
from tf_operator_tpu.runtime.loop import PreemptionGuard, run_training
from tf_operator_tpu.runtime.profiler import Profiler
from tf_operator_tpu.runtime.train import Checkpointer, TrainState


def lm_batches(batch: int, seq_len: int, vocab: int, seed: int):
    print("data: synthetic")
    key = jax.random.PRNGKey(seed)
    while True:
        key, k = jax.random.split(key)
        yield (jax.random.randint(k, (batch, seq_len), 0, vocab),)


def token_record_pipeline(data_dir: str, batch: int, seq_len: int, info):
    """Disjoint per-host shard of pre-tokenized on-disk records — each
    record one [seq_len] int32 token row (write shards with
    data/loader.write_records; shard/prefetch scaffold shared with the
    other examples via data/loader.host_record_batches)."""
    import numpy as np

    from tf_operator_tpu.data.loader import FieldSpec, host_record_batches

    return host_record_batches(
        data_dir, [FieldSpec("tokens", (seq_len,), np.int32)], batch, info,
        lambda rec: (jnp.asarray(rec["tokens"]),),
    )


def make_lm_step(model):
    # tied embedding -> the blocked CE fuses the 128k-vocab lm-head into
    # the loss; no [B,S,V] f32 logits ever materializes
    loss_of = lm_blocked_loss if model.cfg.tie_embeddings else (
        lambda m, p, t: lm_loss(m.apply({"params": p}, t), t)
    )

    def step(state: TrainState, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_of(model, p, tokens)
        )(state.params)
        return state.apply_gradients(grads), {"loss": loss}

    return jax.jit(step, donate_argnums=(0,))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200_000)
    ap.add_argument("--per-host-batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=8192)
    ap.add_argument("--data-dir", default="",
                    help="dir of pre-tokenized .rec shards ([seq-len] "
                         "int32 rows, data/loader.write_records); each "
                         "host reads its disjoint subset. "
                         "Default: synthetic tokens.")
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--save-interval", type=int, default=500)
    ap.add_argument("--tp", type=int, default=1, help="tensor-parallel degree")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel degree (mixtral: all-to-all "
                         "dispatch over this axis)")
    ap.add_argument("--ring", action="store_true",
                    help="sequence-parallel ring attention over tp "
                         "(compact GQA kv shards on the ring; composes "
                         "with mistral's sliding window — out-of-band "
                         "ring hops are skipped statically)")
    ap.add_argument("--model", default="llama3",
                    choices=["llama3", "llama31", "mistral", "mixtral"],
                    help="llama3 = 8B GQA; llama31 = +128k rope scaling; "
                         "mistral = +4k sliding window; mixtral = 8x "
                         "top-2 experts")
    ap.add_argument("--smoke", action="store_true", help="tiny model, CPU ok")
    args = ap.parse_args(argv)

    info = bootstrap.initialize()
    if args.ep > 1 and args.model != "mixtral":
        raise SystemExit(
            f"--ep only applies to --model=mixtral (a dense {args.model} "
            f"has nothing to shard over an expert axis)")
    axes = local_mesh_axes(jax.device_count(), prefer_tp=args.tp)
    if args.ep > 1:
        if axes["dp"] % args.ep:
            raise SystemExit(f"--ep {args.ep} must divide dp {axes['dp']}")
        axes = {**axes, "ep": args.ep, "dp": axes["dp"] // args.ep}
    mesh = make_mesh(axes=axes)
    print(f"host {info.process_id}/{info.num_processes} slice "
          f"{info.slice_id}/{info.num_slices}, mesh {dict(mesh.shape)}")

    if args.ring:
        from tf_operator_tpu.ops.ring_flash import make_ring_flash_attention_fn

        attention_fn = make_ring_flash_attention_fn(mesh, "tp")
    else:
        from tf_operator_tpu.ops.flash_attention import flash_attention

        attention_fn = flash_attention
    presets = {"llama3": llama3_8b, "llama31": llama31_8b,
               "mistral": mistral_7b, "mixtral": mixtral_8x7b}
    extra = {}
    if args.model == "mixtral":
        n_experts = 4 if args.smoke else 8  # one source for the dispatch fn
        if args.ep > 1:
            from tf_operator_tpu.parallel.ep import make_switch_moe

            # the same dispatch fn runs expert-sharded prefill at inference
            extra["moe_dispatch_fn"] = make_switch_moe(
                mesh, n_experts=n_experts, activation="swiglu", top_k=2)
    if args.smoke:
        if args.model == "mixtral":
            extra.update(n_experts=n_experts, moe_every=1, moe_top_k=2)
        if args.model == "mistral":
            extra["sliding_window"] = 16
        cfg = tiny(tie_embeddings=True, attention_fn=attention_fn, **extra)
    else:
        cfg = presets[args.model](tie_embeddings=True, remat=True,
                                  attention_fn=attention_fn, **extra)
        if args.seq_len > cfg.max_len:
            # long-context runs (e.g. mistral at 32k over its 8k preset):
            # extend the RoPE table instead of silently clamping — the
            # whole point of a sliding-window/rope-scaled config is
            # sequences past the preset default
            import dataclasses

            cfg = dataclasses.replace(cfg, max_len=args.seq_len)
    seq_len = min(args.seq_len, cfg.max_len)

    model = Llama(cfg)
    tx = optax.adafactor(1e-3)
    sample = jnp.zeros((args.per_host_batch, seq_len), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), sample, train=False)["params"]
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=tx.init(params), batch_stats={}, tx=tx,
    )
    state = jax.device_put(state, state_sharding(state, mesh))

    if args.data_dir:
        batches = token_record_pipeline(
            args.data_dir, args.per_host_batch, seq_len, info)
    else:
        batches = lm_batches(args.per_host_batch, seq_len, cfg.vocab_size,
                             seed=info.process_id)
    res = run_training(
        state,
        make_lm_step(model),
        batches,
        num_steps=args.steps,
        checkpointer=(
            Checkpointer(args.ckpt_dir, async_save=True)
            if args.ckpt_dir else None
        ),
        save_interval_steps=args.save_interval,
        profiler=Profiler(batch_size=args.per_host_batch * jax.process_count()),
        guard=PreemptionGuard(),
        metrics_sink=print,
    )
    status = "preempted (checkpointed)" if res.preempted else "complete"
    print(f"{status}: steps={res.steps_run} resumed_from={res.resumed_from}")
    return 0 if not res.preempted else 143  # 143 = retryable, gang restarts


if __name__ == "__main__":
    sys.exit(main())
