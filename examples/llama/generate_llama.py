#!/usr/bin/env python
"""[+] Inference CLI: the serving features in one recipe.

Loads llama-family weights from a training checkpoint (orbax, as saved
by train_llama.py) or a LOCAL Hugging Face checkpoint directory
(models/convert.py — Llama or Mixtral, logit-parity-tested), tokenizes a
prompt (built-in byte tokenizer or a local HF tokenizer), and decodes
with any combination of:

  --int8          weight-only int8 quantized decode (models/quant.py):
                  int8 weights stream from HBM each step — the ~2x
                  lever for bandwidth-bound decode
  --int8-kv       int8 KV cache (llama.init_cache kv_quant): the other
                  HBM stream halved; approximate within tested bounds
  --draft-*       exact speculative decoding (models/speculative.py):
                  greedy output is token-identical to plain decoding,
                  temperature sampling is distribution-exact
  --temperature/--top-k/--top-p
                  sampling controls; compose with speculation (both
                  models' distributions truncate + renormalize before
                  the acceptance ratio, keeping emitted tokens exact
                  draws from the truncated target distribution)

Smoke (no checkpoint, random tiny weights, CPU ok):
  python examples/llama/generate_llama.py --smoke --prompt "hello" \
      --max-new 16
"""
import argparse
import sys

import jax
import jax.numpy as jnp

from tf_operator_tpu.data.tokenize import load_tokenizer
from tf_operator_tpu.models import llama
from tf_operator_tpu.models.llama import (
    Llama, llama3_8b, llama31_8b, mistral_7b, mixtral_8x7b, tiny,
)


def load_params(model, cfg, ckpt_dir: str, hf_dir: str,
                smoke: bool = False):
    """Params from an orbax training checkpoint, a local HF checkpoint
    dir, or random init (--smoke ONLY — decoding an 8B model from
    fresh random weights is never what a user without a checkpoint
    flag meant)."""
    if hf_dir:
        import transformers

        from tf_operator_tpu.models.convert import import_hf_llama

        hf = transformers.AutoModelForCausalLM.from_pretrained(
            hf_dir, local_files_only=True)
        return import_hf_llama(hf.state_dict(), cfg)
    if not ckpt_dir and not smoke:
        # refuse BEFORE init: materializing 8B random weights just to
        # error (or worse, decode garbage) helps nobody
        raise SystemExit(
            "no weights: pass --ckpt-dir, --hf-dir, or --smoke "
            "(random tiny weights, testing only)")
    sample = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), sample,
                        train=False)["params"]
    if ckpt_dir:
        from tf_operator_tpu.runtime.train import Checkpointer

        ckpt = Checkpointer(ckpt_dir)
        step = ckpt.latest_step()
        if step is None:
            raise SystemExit(f"no checkpoint under {ckpt_dir}")
        params = ckpt.restore_params(params)
        print(f"restored step {step} from {ckpt_dir}")
        return params
    return params  # --smoke: random weights


def resolve_config(args):
    """Model config from the preset / --smoke / --hf-dir flags — shared
    with the serving CLI (serve_llama.py)."""
    presets = {"llama3": llama3_8b, "llama31": llama31_8b,
               "mistral": mistral_7b, "mixtral": mixtral_8x7b}
    if args.smoke:
        cfg = tiny(tie_embeddings=True, dtype=jnp.float32, max_len=256)
    else:
        cfg = presets[args.model](tie_embeddings=True)
    if args.hf_dir:
        import transformers

        from tf_operator_tpu.models.convert import config_from_hf

        cfg = config_from_hf(
            transformers.AutoConfig.from_pretrained(
                args.hf_dir, local_files_only=True))
    return cfg


def build_draft(args, cfg):
    """(draft model, draft params) from --draft-ckpt-dir/--draft-layers
    (quantized when --int8) — shared with the serving CLI."""
    import dataclasses

    d_layers = args.draft_layers or max(1, cfg.n_layers // 4)
    d_cfg = dataclasses.replace(cfg, n_layers=d_layers)
    d_model = Llama(d_cfg)
    d_params = load_params(d_model, d_cfg, args.draft_ckpt_dir, "",
                           smoke=args.smoke)
    if args.int8:
        from tf_operator_tpu.models import quant

        d_params = quant.quantize_params(d_params)
    return d_model, d_params


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--prompt", required=True)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--model", default="llama3",
                    choices=["llama3", "llama31", "mistral", "mixtral"])
    ap.add_argument("--ckpt-dir", default="",
                    help="orbax checkpoint from train_llama.py")
    ap.add_argument("--hf-dir", default="",
                    help="LOCAL Hugging Face checkpoint directory")
    ap.add_argument("--tokenizer", default="byte",
                    help="'byte' or a local HF tokenizer directory")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 quantized decode")
    ap.add_argument("--int8-kv", action="store_true",
                    help="int8 KV cache (halves the cache HBM stream; "
                         "output approximate within tested bounds)")
    ap.add_argument("--draft-ckpt-dir", default="",
                    help="draft checkpoint -> speculative decoding")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="smoke: random draft with this many layers")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per speculation round")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="prefill the prompt in segments of this size "
                         "(long prompts; sliding-window models stream "
                         "through an O(window) cache)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny random model, CPU ok")
    args = ap.parse_args(argv)

    cfg = resolve_config(args)
    model = Llama(cfg)
    params = load_params(model, cfg, args.ckpt_dir, args.hf_dir,
                         smoke=args.smoke)

    tok = load_tokenizer(args.tokenizer)
    ids = tok.encode(args.prompt)
    if not ids:
        raise SystemExit("empty prompt after tokenization")
    prompt = jnp.asarray(ids, jnp.int32)[None, :]

    gen_kw = {}
    if args.int8:
        from tf_operator_tpu.models import quant

        params = quant.quantize_params(params)
        gen_kw["params_transform"] = quant.make_dequantizer(cfg.dtype)
        print("weights: int8 + per-channel scales")
    if args.int8_kv:
        gen_kw["kv_quant"] = True
        print("kv cache: int8 + per-head scales")

    rng = jax.random.PRNGKey(args.seed)
    speculative = bool(args.draft_ckpt_dir or args.draft_layers)
    if speculative:
        from tf_operator_tpu.models.speculative import speculative_generate

        d_model, d_params = build_draft(args, cfg)
        d_kw = {}
        if args.int8:
            from tf_operator_tpu.models import quant

            d_kw = {"draft_transform": quant.make_dequantizer(cfg.dtype)}
        if args.prefill_chunk:
            # long prompts stream into both rings segment by segment
            # (the library validates chunk | cache etc. itself)
            d_kw["prefill_chunk"] = args.prefill_chunk
        if args.int8_kv:
            d_kw["kv_quant"] = True
        out, stats = speculative_generate(
            model, params, d_model, d_params, prompt, args.max_new,
            k=args.spec_k, temperature=args.temperature, rng=rng,
            eos_id=tok.eos_id, top_k=args.top_k, top_p=args.top_p,
            target_transform=gen_kw.get("params_transform"),
            return_stats=True, **d_kw)
        print(f"speculative: {stats['target_forwards']} target forwards "
              f"for {args.max_new} tokens (plain decode = {args.max_new})")
    else:
        if args.prefill_chunk:
            # forward verbatim (including invalid values: the library's
            # own validation message beats a silent mask here)
            gen_kw["prefill_chunk"] = args.prefill_chunk
        out = llama.generate(
            model, params, prompt, args.max_new, rng=rng,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, eos_id=tok.eos_id, **gen_kw)

    ids_out = [int(t) for t in out[0]]
    print(tok.decode(ids_out))
    print(f"tokens: {ids_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
