#!/usr/bin/env python
"""[+] Serving CLI: continuous batching over a request list.

Feeds a batch of prompts through `models/serving.serve_loop` — a fixed
set of decode lanes with slot admission (a finished request frees its
lane and the next queued prompt prefills into it while every other lane
keeps decoding).  Every serving feature composes here:

  --slots N          decode lanes (the static batch whose occupancy
                     changes)
  --int8 / --int8-kv weight-only int8 decode / int8 KV caches
  --draft-layers K   SPECULATIVE serving: per-lane draft+verify rounds
                     (spec_k tokens per round) through the same lanes
  --temperature/--top-k/--top-p   sampling (composes with speculation)
  --prefill-chunk    long prompts stream into each lane's cache in
                     segments
  --steps-per-sync   decode-block size between host syncs (scheduling
                     only — tokens are invariant, test_block_size_...)

Prompts come one per line from --prompts-file, or from repeated
--prompt flags.  Outputs print in request order with scheduling
metadata (slot, admitted/finished step) and aggregate tokens/sec.

Smoke (no checkpoint, random tiny weights, CPU ok):
  python examples/llama/serve_llama.py --smoke \
      --prompt "hello" --prompt "the quick brown fox" --max-new 16

Weight loading (checkpoint / local-HF / tokenizer flags) is shared with
generate_llama.py.  No reference counterpart (the reference has no
serving code, SURVEY.md §5.7).
"""
import argparse
import sys
import time

import jax
import jax.numpy as jnp

from tf_operator_tpu.data.tokenize import load_tokenizer
from tf_operator_tpu.models.llama import Llama
from tf_operator_tpu.models.serving import serve_loop


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--prompt", action="append", default=[],
                    help="repeatable; one request per flag")
    ap.add_argument("--prompts-file", default="",
                    help="one prompt per line")
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--steps-per-sync", type=int, default=8)
    ap.add_argument("--model", default="llama3",
                    choices=["llama3", "llama31", "mistral", "mixtral"])
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--hf-dir", default="")
    ap.add_argument("--tokenizer", default="byte")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--int8", action="store_true")
    ap.add_argument("--int8-kv", action="store_true")
    ap.add_argument("--draft-ckpt-dir", default="",
                    help="draft checkpoint -> speculative serving")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="smoke: random draft with this many layers")
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--system-prompt", default="",
                    help="shared prefix prepended to every request but "
                         "prefilled ONCE (prefix caching); with "
                         "--prefill-chunk its token length must be a "
                         "chunk multiple")
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache: block-pool attention with "
                         "memory-gated admission and copy-free prefix "
                         "sharing (models/paging.py)")
    ap.add_argument("--block-size", type=int, default=64,
                    help="KV block size in tokens (with --paged)")
    ap.add_argument("--pool-blocks", type=int, default=0,
                    help="block-pool capacity; 0 = dense-equivalent "
                         "default (every lane can hold the worst case)")
    ap.add_argument("--paged-kernel", default="auto",
                    choices=["auto", "pallas", "gather"],
                    help="paged read path: 'pallas' = block-indexed "
                         "pallas decode kernel (interpret-mode on "
                         "CPU), 'gather' = table-gathered linear view "
                         "(the parity oracle), 'auto' = pallas on TPU "
                         "/ gather on CPU and under tensor "
                         "parallelism")
    ap.add_argument("--prefill-chunk", type=int, default=0)
    ap.add_argument("--prefill-chunks-per-sync", type=int, default=0,
                    help="admission-stall bound: stream at most this "
                         "many prompt segments per decode block (long "
                         "prompts no longer stall the other lanes)")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)

    prompts = list(args.prompt)
    if args.prompts_file:
        with open(args.prompts_file) as f:
            prompts += [line.rstrip("\n") for line in f if line.strip()]
    if not prompts:
        raise SystemExit("no requests: pass --prompt or --prompts-file")

    # model/weights/draft setup shared with the generation CLI (loaded
    # by path — examples/ is scripts, not a package)
    import importlib.util
    import os

    _spec = importlib.util.spec_from_file_location(
        "generate_llama",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "generate_llama.py"))
    _gen = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(_gen)

    cfg = _gen.resolve_config(args)
    model = Llama(cfg)
    params = _gen.load_params(model, cfg, args.ckpt_dir, args.hf_dir,
                              smoke=args.smoke)

    tok = load_tokenizer(args.tokenizer)
    requests = []
    for i, p in enumerate(prompts):
        ids = tok.encode(p)
        if not ids:
            raise SystemExit(
                f"request {i} ({p!r}): empty prompt after tokenization")
        requests.append(jnp.asarray(ids, jnp.int32))

    # top_k/top_p forward verbatim (including invalid values: the
    # library's own validation message beats a silent mask here)
    kw = {"top_k": args.top_k, "top_p": args.top_p}
    if args.int8:
        from tf_operator_tpu.models import quant

        params = quant.quantize_params(params)
        kw["params_transform"] = quant.make_dequantizer(cfg.dtype)
        print("weights: int8 + per-channel scales")
    if args.int8_kv:
        kw["kv_quant"] = True
        print("kv caches: int8 + per-head scales")
    if args.prefill_chunk:
        kw["prefill_chunk"] = args.prefill_chunk
    if args.prefill_chunks_per_sync:
        kw["prefill_chunks_per_sync"] = args.prefill_chunks_per_sync
    if args.system_prompt:
        pfx = tok.encode(args.system_prompt)
        kw["shared_prefix"] = jnp.asarray(pfx, jnp.int32)
        print(f"system prompt: {len(pfx)} tokens, prefilled once")
    if args.temperature > 0.0:
        kw.update(temperature=args.temperature,
                  rng=jax.random.PRNGKey(args.seed))

    if args.draft_ckpt_dir or args.draft_layers:
        d_model, d_params = _gen.build_draft(args, cfg)
        if args.int8:
            from tf_operator_tpu.models import quant

            kw["draft_transform"] = quant.make_dequantizer(cfg.dtype)
        kw.update(draft=d_model, draft_params=d_params,
                  spec_k=args.spec_k)
        print(f"speculative serving: {d_model.cfg.n_layers}-layer "
              f"draft, k={args.spec_k}")

    if args.paged:
        kw.update(paged=True, block_size=args.block_size)
        if args.pool_blocks:
            kw["pool_blocks"] = args.pool_blocks
        if args.paged_kernel != "auto":
            kw["paged_kernel"] = args.paged_kernel
        print(f"paged KV cache: block_size={args.block_size}, "
              f"pool_blocks={args.pool_blocks or 'auto'}, "
              f"kernel={args.paged_kernel}")

    t0 = time.perf_counter()
    results = serve_loop(model, params, requests, slots=args.slots,
                         max_new_tokens=args.max_new,
                         eos_id=tok.eos_id,
                         steps_per_sync=args.steps_per_sync, **kw)
    dt = time.perf_counter() - t0
    n_tokens = sum(len(r.tokens) for r in results)
    for i, (p, r) in enumerate(zip(prompts, results)):
        spec_note = (
            f", acceptance {r.accepted_drafts}/{r.proposed_drafts}"
            if r.proposed_drafts else "")
        print(f"--- request {i} (slot {r.slot}, steps "
              f"{r.admitted_at_step}->{r.finished_at_step}{spec_note})")
        print(f"    {p!r} -> {tok.decode(r.tokens)!r}")
    print(f"{len(requests)} requests, {n_tokens} tokens through "
          f"{args.slots} lanes in {dt:.2f}s "
          f"({n_tokens / dt:.1f} tok/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
