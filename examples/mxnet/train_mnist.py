#!/usr/bin/env python
"""MXNet KVStore training entry: reads the DMLC rendezvous contract the
operator injects (MX_CONFIG + DMLC_* — docs/env_contract.md, the
reference mxnet.go:55-120 contract) and launches real MXNet training when
the framework is available, else validates the env round-trip so the
example stays runnable (and run-local testable) without mxnet installed.

In production the container runs MXNet directly: `mxnet.kvstore.create
('dist_sync')` reads DMLC_ROLE / DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT /
DMLC_NUM_SERVER / DMLC_NUM_WORKER from the environment, so the
operator-injected values need no flag plumbing at all.
"""
import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--kv-store", default="dist_sync")
    args = ap.parse_args(argv)

    role = os.environ.get("DMLC_ROLE", "")
    contract = {
        k: os.environ.get(k, "")
        for k in (
            "DMLC_ROLE",
            "DMLC_PS_ROOT_URI",
            "DMLC_PS_ROOT_PORT",
            "DMLC_NUM_SERVER",
            "DMLC_NUM_WORKER",
            "DMLC_USE_KUBERNETES",
        )
    }
    missing = [k for k, v in contract.items() if not v and k != "DMLC_USE_KUBERNETES"]
    if missing:
        print(f"not an MXJob pod: missing {missing}", file=sys.stderr)
        return 1
    for k, v in contract.items():
        print(f"{k}={v}", flush=True)

    mx_config = json.loads(os.environ.get("MX_CONFIG", "{}"))
    task = mx_config.get("task", {})
    assert task.get("type", "").lower() == role.lower(), (task, role)
    cluster = mx_config.get("cluster", {})
    assert int(contract["DMLC_NUM_WORKER"]) == len(cluster.get("worker", [])), (
        contract, cluster,
    )
    print(f"mx contract ok: role={role} task_index={task.get('index')}",
          flush=True)

    try:
        import mxnet  # noqa: F401 — real training only with the framework
    except ImportError:
        print("mxnet not installed: contract validated, exiting 0", flush=True)
        return 0
    # real path: kvstore reads the DMLC env directly
    import mxnet as mx

    kv = mx.kvstore.create(args.kv_store)
    print(f"kvstore rank={kv.rank}/{kv.num_workers}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
