#!/usr/bin/env python
"""Config #2: distributed MNIST under the PS+Worker topology.

Reads the operator-injected TF_CONFIG (the same contract the reference's
dist_mnist.py consumes, reference examples/v1/dist-mnist/dist_mnist.py) and
reports its role. PS replicas idle-serve (TF parameter-server semantics
live in TF containers); workers run data-parallel training over their local
devices — demonstrating that the env contract carries everything a
framework needs to self-assemble.
"""
import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.mnist import MnistMLP
from tf_operator_tpu.runtime.loop import run_training
from tf_operator_tpu.runtime.train import create_train_state, make_train_step


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1000)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args(argv)

    tf_config = json.loads(os.environ.get("TF_CONFIG", "{}"))
    task = tf_config.get("task", {})
    role, index = task.get("type", "worker"), task.get("index", 0)
    cluster = tf_config.get("cluster", {})
    print(f"role={role} index={index} cluster_keys={sorted(cluster)}")

    if role == "ps":
        # a TF parameter server would block serving variables here; the
        # JAX-native path has no PS — exit cleanly so the job can succeed
        # under the worker-0 success rule
        print("ps replica: parameter serving is framework-internal; idling")
        return 0

    model = MnistMLP()
    sample = jnp.zeros((args.batch_size, 28, 28, 1))
    state = create_train_state(
        jax.random.PRNGKey(index), model, sample, optax.sgd(0.01)
    )

    def batches():
        key = jax.random.PRNGKey(1000 + index)  # per-worker data shard
        while True:
            key, k1, k2 = jax.random.split(key, 3)
            yield (
                jax.random.normal(k1, (args.batch_size, 28, 28, 1)),
                jax.random.randint(k2, (args.batch_size,), 0, 10),
            )

    res = run_training(
        state, make_train_step(model), batches(),
        num_steps=args.steps, metrics_sink=print,
    )
    print(f"worker {index} done: steps={res.steps_run}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
