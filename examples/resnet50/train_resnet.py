#!/usr/bin/env python
"""Config #3: ResNet-50 data-parallel all-reduce training (BASELINE.md
north-star metric: images/sec/chip on a TPU slice).

jax.distributed bootstraps from the operator-injected env
(COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, controllers/tpu.py);
the global mesh spans every chip in the slice; XLA turns the gradient mean
into an ICI all-reduce — the reference delegates the identical topology to
MultiWorkerMirroredStrategy+NCCL inside GPU containers.
"""
import argparse
import sys

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.resnet import ResNet50
from tf_operator_tpu.parallel.mesh import make_mesh
from tf_operator_tpu.runtime import bootstrap
from tf_operator_tpu.runtime.loop import PreemptionGuard, run_training
from tf_operator_tpu.runtime.profiler import Profiler
from tf_operator_tpu.runtime.train import (
    Checkpointer,
    create_train_state,
    make_train_step,
)


def synthetic_imagenet(batch: int, image_size: int, seed: int):
    key = jax.random.PRNGKey(seed)
    while True:
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (batch, image_size, image_size, 3), jnp.bfloat16)
        y = jax.random.randint(k2, (batch,), 0, 1000)
        yield (x, y)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5000)
    ap.add_argument("--per-host-batch", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--ckpt-dir", default="")
    args = ap.parse_args(argv)

    info = bootstrap.initialize()
    mesh = make_mesh({"dp": -1})  # all devices on the dp axis
    print(f"host {info.process_id}/{info.num_processes}: "
          f"{jax.device_count()} chips, mesh {dict(mesh.shape)}")

    model = ResNet50(num_classes=1000)
    sample = jnp.zeros((args.per_host_batch, args.image_size, args.image_size, 3),
                       jnp.bfloat16)
    state = create_train_state(
        jax.random.PRNGKey(0), model, sample,
        optax.sgd(0.1 * jax.process_count(), momentum=0.9),
    )
    step_fn = make_train_step(model, mesh=mesh)
    res = run_training(
        state,
        step_fn,
        synthetic_imagenet(args.per_host_batch, args.image_size,
                           seed=info.process_id),
        num_steps=args.steps,
        checkpointer=Checkpointer(args.ckpt_dir) if args.ckpt_dir else None,
        profiler=Profiler(batch_size=args.per_host_batch * jax.process_count()),
        guard=PreemptionGuard(),
        metrics_sink=print,
    )
    print(f"done: steps={res.steps_run} preempted={res.preempted}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
