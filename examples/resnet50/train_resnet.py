#!/usr/bin/env python
"""Config #3: ResNet-50 data-parallel all-reduce training (BASELINE.md
north-star metric: images/sec/chip on a TPU slice).

jax.distributed bootstraps from the operator-injected env
(COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, controllers/tpu.py);
the global mesh spans every chip in the slice; XLA turns the gradient mean
into an ICI all-reduce — the reference delegates the identical topology to
MultiWorkerMirroredStrategy+NCCL inside GPU containers.
"""
import argparse
import sys

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.resnet import ResNet50
from tf_operator_tpu.parallel.mesh import make_mesh
from tf_operator_tpu.runtime import bootstrap
from tf_operator_tpu.runtime.loop import PreemptionGuard, run_training
from tf_operator_tpu.runtime.profiler import Profiler
from tf_operator_tpu.runtime.train import (
    Checkpointer,
    create_train_state,
    make_train_step,
)


def synthetic_imagenet(batch: int, image_size: int, seed: int):
    key = jax.random.PRNGKey(seed)
    while True:
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (batch, image_size, image_size, 3), jnp.bfloat16)
        y = jax.random.randint(k2, (batch,), 0, 1000)
        yield (x, y)


def record_pipeline(data_dir: str, batch: int, image_size: int, info):
    """Disjoint per-host shard of on-disk records (the tf.data auto-shard
    analogue; shard/prefetch scaffold shared with the other examples via
    data/loader.host_record_batches, native C++ reader when built)."""
    import numpy as np

    from tf_operator_tpu.data.loader import FieldSpec, host_record_batches

    def to_batch(rec):
        x = jnp.asarray(rec["image"], jnp.bfloat16) / 127.5 - 1.0
        return (x, jnp.asarray(rec["label"]))

    return host_record_batches(
        data_dir,
        [FieldSpec("image", (image_size, image_size, 3), np.uint8),
         FieldSpec("label", (), np.int32)],
        batch, info, to_batch,
    )


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5000)
    ap.add_argument("--per-host-batch", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--data-dir", default="",
                    help=".rec shards (data/loader.py format); each host "
                         "reads its disjoint subset. Default: synthetic.")
    ap.add_argument("--trace-dir", default="",
                    help="capture an XProf device trace of steps 10-30 "
                         "(runtime/profiler.py bounded window)")
    args = ap.parse_args(argv)

    info = bootstrap.initialize()
    mesh = make_mesh({"dp": -1})  # all devices on the dp axis
    print(f"host {info.process_id}/{info.num_processes}: "
          f"{jax.device_count()} chips, mesh {dict(mesh.shape)}")

    model = ResNet50(num_classes=1000)
    sample = jnp.zeros((args.per_host_batch, args.image_size, args.image_size, 3),
                       jnp.bfloat16)
    state = create_train_state(
        jax.random.PRNGKey(0), model, sample,
        optax.sgd(0.1 * jax.process_count(), momentum=0.9),
    )
    step_fn = make_train_step(model, mesh=mesh)
    if args.data_dir:
        data = record_pipeline(args.data_dir, args.per_host_batch,
                               args.image_size, info)
    else:
        print("data: synthetic")
        data = synthetic_imagenet(args.per_host_batch, args.image_size,
                                  seed=info.process_id)
    res = run_training(
        state,
        step_fn,
        data,
        num_steps=args.steps,
        checkpointer=Checkpointer(args.ckpt_dir) if args.ckpt_dir else None,
        profiler=Profiler(trace_dir=args.trace_dir or None,
                          batch_size=args.per_host_batch * jax.process_count()),
        guard=PreemptionGuard(),
        metrics_sink=print,
    )
    print(f"done: steps={res.steps_run} preempted={res.preempted}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
