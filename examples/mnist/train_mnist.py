#!/usr/bin/env python
"""Config #1: single-replica MNIST CNN (BASELINE.md ladder).

Runs the framework's full runtime path on one host: bootstrap (no-op env),
jitted train step, checkpoint/resume, metrics lines. Synthetic MNIST-shaped
data keeps the example hermetic (no dataset download; swap `synthetic_mnist`
for a real loader in production).
"""
import argparse
import sys

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.mnist import MnistCNN
from tf_operator_tpu.runtime import bootstrap
from tf_operator_tpu.runtime.loop import PreemptionGuard, run_training
from tf_operator_tpu.runtime.profiler import Profiler
from tf_operator_tpu.runtime.train import (
    Checkpointer,
    create_train_state,
    make_train_step,
)


def synthetic_mnist(batch_size: int, seed: int = 0):
    key = jax.random.PRNGKey(seed)
    while True:
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (batch_size, 28, 28, 1), jnp.float32)
        y = jax.random.randint(k2, (batch_size,), 0, 10)
        yield (x, y)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--log-interval", type=int, default=50)
    args = ap.parse_args(argv)

    info = bootstrap.initialize()
    print(f"process {info.process_id}/{info.num_processes}, "
          f"devices={jax.device_count()}")

    model = MnistCNN()
    sample = jnp.zeros((args.batch_size, 28, 28, 1))
    state = create_train_state(
        jax.random.PRNGKey(0), model, sample, optax.adam(1e-3)
    )
    step_fn = make_train_step(model)
    res = run_training(
        state,
        step_fn,
        synthetic_mnist(args.batch_size),
        num_steps=args.steps,
        checkpointer=Checkpointer(args.ckpt_dir) if args.ckpt_dir else None,
        profiler=Profiler(batch_size=args.batch_size),
        guard=PreemptionGuard(),
        log_interval_steps=args.log_interval,
        metrics_sink=print,
    )
    print(f"done: steps={res.steps_run} loss={res.last_metrics.get('loss')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
