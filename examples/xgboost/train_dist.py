#!/usr/bin/env python
"""XGBoost rabit training entry: reads the rendezvous contract the
operator injects (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK —
docs/env_contract.md, the reference xgboost.go:18-100 contract) and runs
real distributed XGBoost when the framework is available, else validates
the env round-trip so the example stays runnable (and run-local
testable) without xgboost installed.

In production the master runs the rabit tracker on MASTER_ADDR:PORT and
every replica joins with its RANK out of WORLD_SIZE.
"""
import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--job_type", default="Train")
    ap.add_argument("--xgboost_parameter", default="")
    args = ap.parse_args(argv)

    contract = {
        k: os.environ.get(k, "")
        for k in ("MASTER_ADDR", "MASTER_PORT", "WORLD_SIZE", "RANK")
    }
    missing = [k for k, v in contract.items() if not v]
    if missing:
        print(f"not an XGBoostJob pod: missing {missing}", file=sys.stderr)
        return 1
    for k, v in contract.items():
        print(f"{k}={v}", flush=True)
    rank, world = int(contract["RANK"]), int(contract["WORLD_SIZE"])
    assert 0 <= rank < world, (rank, world)
    print(f"xgb contract ok: rank={rank}/{world} job_type={args.job_type}",
          flush=True)

    try:
        import xgboost  # noqa: F401 — real training only with the framework
    except ImportError:
        print("xgboost not installed: contract validated, exiting 0",
              flush=True)
        return 0
    # real path: start/join the rabit tracker from the injected env
    from xgboost import collective

    with collective.CommunicatorContext(
        dmlc_tracker_uri=contract["MASTER_ADDR"],
        dmlc_tracker_port=int(contract["MASTER_PORT"]),
        dmlc_task_id=str(rank), dmlc_num_worker=world,
    ):
        print(f"rabit rank={collective.get_rank()}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
