from tf_operator_tpu.parallel.mesh import (
    MeshRules,
    make_mesh,
    named_sharding,
    DEFAULT_RULES,
)

__all__ = ["MeshRules", "make_mesh", "named_sharding", "DEFAULT_RULES"]
