"""Pipeline parallelism — GPipe-style microbatch schedule over the `pp`
mesh axis.

Absent from the reference (SURVEY.md §2.10: PP row "NO"). TPU-first
design: stage parameters are stacked on a leading dim and sharded over
`pp`, every device runs the same scanned schedule (SPMD — no per-stage
programs), and activations hop one ICI neighbor per tick via
`jax.lax.ppermute`. A microbatch enters stage 0 each tick; after the
pipeline fills, all stages compute concurrently; outputs drain from the
last stage. Total ticks = n_micro + n_stages - 1, bubble fraction
(n_stages-1)/(n_micro+n_stages-1).

Autodiff runs through scan + ppermute, which yields the reverse schedule
(activation hops transpose to backward hops) without a hand-written
backward pipeline.
"""
from __future__ import annotations

import functools
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(params_list) -> Any:
    """[per-stage pytrees] -> one pytree with a leading stage dim, ready to
    shard with PartitionSpec('pp', ...)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *params_list
    )


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array], params, x,
          *, axis_name: str = "pp", has_aux: bool = False,
          aux_mean_axes: tuple = ()):
    """Run the pipeline. Call inside shard_map:
      params — this device's stage slice, leading dim 1 (from a stacked
               [n_stages, ...] pytree sharded over `axis_name`)
      x      — microbatched input [n_micro, mb, ...], same on every stage
    Returns [n_micro, mb, ...] outputs (replicated via a masked psum).

    has_aux: stage_fn returns (y, aux_scalar) — e.g. an MoE load-balance
    loss.  Each stage accumulates aux only on its VALID ticks (the
    fill/drain ticks compute on garbage and must not contribute), the
    per-stage sums are psummed over `axis_name` (total over stages ×
    microbatches), then pmeaned over `aux_mean_axes` (token-splitting
    axes: each member saw different tokens, the global scalar is their
    mean).  Returns (outputs, aux_total)."""
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if leaf.ndim == 0 or leaf.shape[0] != 1:
            stages = "a scalar (no stage dim)" if leaf.ndim == 0 else leaf.shape[0]
            raise ValueError(
                f"gpipe: per-device param {jax.tree_util.keystr(path)} carries "
                f"{stages}; the stacked stage dim must equal the "
                f"{axis_name!r} axis size ({n_stages})"
            )
    my_params = jax.tree_util.tree_map(lambda p: p[0], params)
    n_micro = x.shape[0]
    ticks = n_micro + n_stages - 1
    # activations hop stage i -> i+1; stage 0 has no upstream sender
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def run_stage(inp):
        if has_aux:
            return stage_fn(my_params, inp)
        return stage_fn(my_params, inp), jnp.float32(0)

    def tick(carry, t):
        buf, out, aux_acc = carry
        feed = x[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(stage == 0, feed, buf)
        y, aux = run_stage(inp)
        buf_next = jax.lax.ppermute(y, axis_name, perm)
        # this stage computes microbatch t - stage; outside [0, n_micro)
        # it's chewing fill/drain garbage and the aux must be masked
        m_mine = t - stage
        aux_valid = jnp.logical_and(m_mine >= 0, m_mine < n_micro)
        aux_acc = aux_acc + jnp.where(
            aux_valid, aux.astype(jnp.float32), 0.0
        )
        m = t - (n_stages - 1)  # microbatch draining at the last stage
        valid = jnp.logical_and(stage == n_stages - 1,
                                jnp.logical_and(m >= 0, m < n_micro))
        upd = jnp.where(valid, y, out[jnp.clip(m, 0, n_micro - 1)])
        out = jax.lax.dynamic_update_index_in_dim(
            out, upd, jnp.clip(m, 0, n_micro - 1), axis=0)
        return (buf_next, out, aux_acc), None

    y_struct = _stage_out_struct_aux(run_stage, x)
    buf0 = jnp.zeros(y_struct.shape, y_struct.dtype)
    out0 = jnp.zeros((n_micro,) + y_struct.shape, y_struct.dtype)
    (_, out, aux_acc), _ = jax.lax.scan(
        tick, (buf0, out0, jnp.float32(0)), jnp.arange(ticks)
    )
    # only the last stage holds real outputs; replicate with a masked psum
    mask = (stage == n_stages - 1).astype(out.dtype)
    out = jax.lax.psum(out * mask, axis_name)
    if not has_aux:
        return out
    aux_total = jax.lax.psum(aux_acc, axis_name)
    for ax in aux_mean_axes:
        aux_total = jax.lax.pmean(aux_total, ax)
    return out, aux_total


def _stage_out_struct_aux(run_stage, x):
    """Shape+dtype of one stage's output on the steady-state carry. Stages
    must be shape-preserving across hops; the carry dtype is the fixed point
    of input-dtype promotion (a bf16 batch through f32 params carries f32).
    run_stage: inp -> (y, aux)."""
    y, _ = jax.eval_shape(run_stage, jax.ShapeDtypeStruct(x.shape[1:], x.dtype))
    carry_dtype = jnp.promote_types(x.dtype, y.dtype)
    y, _ = jax.eval_shape(run_stage,
                          jax.ShapeDtypeStruct(x.shape[1:], carry_dtype))
    if y.shape != x.shape[1:]:
        raise ValueError(
            f"gpipe: stage output shape {y.shape} != input {x.shape[1:]}; "
            f"stages must be shape-preserving"
        )
    return jax.ShapeDtypeStruct(y.shape, jnp.promote_types(carry_dtype, y.dtype))


def make_pipeline_fn(mesh: Mesh, stage_fn, n_micro: int,
                     axis_name: str = "pp", param_specs=None,
                     batch_axes=None, has_aux: bool = False):
    """jit-able f(stacked_params, batch) running the pipeline over `mesh`.
    `stacked_params` leaves are [n_stages, ...]; batch [B, ...] is split
    into n_micro microbatches.

    param_specs: optional PartitionSpec pytree for the stacked params
    (prefix-pytrees allowed, as shard_map accepts) when stage params are
    sharded beyond the leading `axis_name` dim — e.g. tensor-parallel
    head/ffn dims whose collectives stage_fn places itself.  Default:
    everything sharded only over `axis_name`.
    batch_axes: optional mesh axis (or tuple) to shard the microbatch dim
    over (data parallelism inside the pipeline).  Default: replicated.
    has_aux: stage_fn returns (y, aux_scalar); f returns (out, aux_total)
    with aux summed over stages × microbatches and pmeaned over the
    token-splitting axes (see gpipe)."""
    from tf_operator_tpu.parallel.compat import shard_map

    if param_specs is None:
        param_specs = P(axis_name)
    if batch_axes is not None:
        if isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        batch_axes = tuple(batch_axes)
        missing = [a for a in batch_axes if a not in mesh.shape]
        if missing:
            raise ValueError(
                f"batch_axes {missing} not in mesh axes "
                f"{tuple(mesh.shape)} (batch_axes must name mesh axes to "
                f"shard the microbatch dim over)"
            )
        x_spec = P(None, batch_axes)
        dp_total = math.prod(mesh.shape[a] for a in batch_axes)
    else:
        x_spec = P()
        dp_total = 1

    def run(params, batch):
        b = batch.shape[0]
        if b % n_micro:
            raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
        if (b // n_micro) % dp_total:
            raise ValueError(
                f"microbatch {b // n_micro} not divisible by the batch mesh "
                f"axes {batch_axes} (total {dp_total})"
            )
        pp = mesh.shape[axis_name]
        for path, leaf in jax.tree_util.tree_leaves_with_path(params):
            if leaf.ndim == 0 or leaf.shape[0] != pp:
                stages = "a scalar (no stage dim)" if leaf.ndim == 0 else leaf.shape[0]
                raise ValueError(
                    f"make_pipeline_fn: stacked param "
                    f"{jax.tree_util.keystr(path)} has {stages} stages but "
                    f"mesh axis {axis_name!r} has {pp} devices; they must "
                    f"match (one stage per pipeline device)"
                )
        x = batch.reshape((n_micro, b // n_micro) + batch.shape[1:])
        # the aux scalar differs across members that saw different tokens
        # (the batch axes); pmean over every non-pp axis is the global mean
        # (size-1 and replicated axes are no-ops)
        aux_mean_axes = tuple(a for a in mesh.axis_names if a != axis_name)
        inner = functools.partial(
            gpipe, stage_fn, axis_name=axis_name,
            has_aux=has_aux, aux_mean_axes=aux_mean_axes,
        )
        out_specs = (x_spec, P()) if has_aux else x_spec
        out = shard_map(
            inner, mesh=mesh,
            in_specs=(param_specs, x_spec), out_specs=out_specs,
            check_rep=False,
        )(params, x)
        if has_aux:
            out, aux = out
            return out.reshape((b,) + out.shape[2:]), aux
        return out.reshape((b,) + out.shape[2:])

    return run
