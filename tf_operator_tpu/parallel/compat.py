"""Version shims for jax APIs that moved between releases."""
from __future__ import annotations

import inspect

try:  # jax >= 0.6: top-level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_rep: bool = True):
    """shard_map with the old `check_rep` name; newer jax calls it
    `check_vma` (varying-manual-axes checking)."""
    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if "check_rep" in _PARAMS:
        kw["check_rep"] = check_rep
    elif "check_vma" in _PARAMS:
        kw["check_vma"] = check_rep
    return _shard_map(f, **kw)
