"""Tensor-parallel (Megatron-style) + expert + fsdp param placement for the
transformer family.

Column-parallel qkv/wi (shard the output features over tp), row-parallel
out/wo (shard the input features over tp) — XLA then inserts exactly one
all-reduce per attention/MLP block over the tp axis of the mesh (ICI).
Experts shard over ep; everything else optionally overlays fsdp on its
largest free dim. No reference counterpart: the reference operator never
touches tensors (SURVEY.md §2.10 TP row: absent).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def _overlay_fsdp(spec_list, shape, fsdp: int, min_size: int):
    from tf_operator_tpu.parallel.mesh import pick_fsdp_dim

    taken = tuple(d for d, s in enumerate(spec_list) if s is not None)
    d = pick_fsdp_dim(shape, fsdp, min_size, taken=taken)
    if d is not None:
        spec_list[d] = "fsdp"
    return spec_list


def transformer_param_sharding(
    params: Any, mesh: Mesh, min_fsdp_size: int = 2**14
) -> Any:
    """Pytree of NamedSharding matching `params` (from models/transformer.py).

    Weight-only-quantized trees (models/quant.QTensor leaves) place by the
    SAME rule table: the int8 payload takes the rule for its param name,
    and the per-output-channel scale inherits the payload's spec on every
    dim it actually carries (broadcast size-1 dims replicate — a
    row-parallel kernel's scale has no input dim to shard)."""
    from tf_operator_tpu.models.quant import QTensor

    tp = mesh.shape.get("tp", 1)
    ep = mesh.shape.get("ep", 1)
    fsdp = mesh.shape.get("fsdp", 1)

    def place(path, x):
        name = _path_str(path)
        shape = getattr(x, "shape", ())  # QTensor.shape is its q.shape
        spec = [None] * len(shape)

        def ok(dim, axis_size):
            return dim < len(shape) and shape[dim] % axis_size == 0

        if tp > 1:
            if name.endswith("qkv/kernel") and ok(2, tp):
                spec[2] = "tp"  # [E, 3, H, D]: shard heads
            elif name.endswith("attn/wq/kernel") and ok(1, tp):
                spec[1] = "tp"  # llama [E, H, D]: shard query heads
            elif name.endswith("attn/wkv/kernel") and ok(2, tp):
                spec[2] = "tp"  # llama [E, 2, KV, D]: shard kv heads
            elif "attn/out/kernel" in name and ok(0, tp):
                spec[0] = "tp"  # [H, D, E]: row-parallel
            elif (name.endswith("mlp/wi/kernel") and len(shape) == 3
                    and ok(2, tp)):
                spec[2] = "tp"  # llama swiglu [E, 2, F]: column-parallel
            elif name.endswith("mlp/wi/kernel") and ok(1, tp):
                spec[1] = "tp"  # [E, F]: column-parallel
            elif name.endswith("mlp/wo/kernel") and ok(0, tp):
                spec[0] = "tp"  # [F, E]: row-parallel
            elif name.endswith("embed/embedding") and ok(0, tp):
                spec[0] = "tp"  # vocab-parallel embedding
            elif name.endswith("lm_head/kernel") and ok(1, tp):
                spec[1] = "tp"
            elif name.endswith("moe/wi") and ok(2, tp):
                spec[2] = "tp"  # [X, D, F]
            elif name.endswith("moe/wo") and ok(1, tp):
                spec[1] = "tp"  # [X, F, D]
        if ep > 1 and ("moe/wi" in name or "moe/wo" in name) and ok(0, ep):
            spec[0] = "ep"  # experts over ep
        spec = _overlay_fsdp(spec, shape, fsdp, min_fsdp_size)
        if isinstance(x, QTensor):
            sspec = [
                a if a is not None and x.scale.shape[d] % mesh.shape[a] == 0
                else None
                for d, a in enumerate(spec)
            ]
            return QTensor(q=NamedSharding(mesh, P(*spec)),
                           scale=NamedSharding(mesh, P(*sspec)))
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(
        place, params, is_leaf=lambda x: isinstance(x, QTensor))


def kv_cache_sharding(cfg, mesh: Mesh, batch: int) -> NamedSharding:
    """Sharding for the decode KV cache (models/llama.init_cache leaves,
    [B, C, KV, D]): kv heads over tp — each chip holds only its own
    heads' K/V, the HBM stream that dominates long-context decode — and
    batch over the data axes (dcn/dp/fsdp) when it divides.  Axes that
    do not divide replicate rather than refuse: a 70B model with 8 kv
    heads on a tp=16 mesh still serves, it just replicates the cache
    within each 2-chip group.

    The positions dim (C) is deliberately never sharded: every decode
    step writes one slot at a dynamic position, and a sharded C would
    turn each write into cross-chip traffic."""
    tp = mesh.shape.get("tp", 1)
    data_axes = tuple(a for a in ("dcn", "dp", "fsdp")
                      if mesh.shape.get(a, 1) > 1)
    n_data = 1
    for a in data_axes:
        n_data *= mesh.shape[a]
    spec_b = data_axes if data_axes and batch % n_data == 0 else None
    spec_kv = "tp" if tp > 1 and cfg.n_kv_heads % tp == 0 else None
    return NamedSharding(mesh, P(spec_b, None, spec_kv, None))


def state_sharding(state, mesh: Mesh, param_fn=transformer_param_sharding):
    """Sharding for a TrainState: params + mirrored opt_state, scalars
    replicated."""
    params_sh = param_fn(state.params, mesh)

    # optax states mirror the param tree where shapes match (momenta etc.);
    # shard those like their params, replicate scalars/counts
    flat_params = jax.tree.leaves_with_path(state.params)
    by_shape = {}
    for path, leaf in flat_params:
        by_shape.setdefault(getattr(leaf, "shape", ()), []).append(path)
    params_sh_flat = {tuple(p): s for p, s in jax.tree.leaves_with_path(params_sh)}

    def place_opt(path, leaf):
        shape = getattr(leaf, "shape", ())
        if not shape:
            return NamedSharding(mesh, P())
        cands = by_shape.get(shape)
        if cands:
            return params_sh_flat[tuple(cands[0])]
        return NamedSharding(mesh, P())

    opt_sh = jax.tree_util.tree_map_with_path(place_opt, state.opt_state)
    bs_sh = jax.tree.map(lambda x: NamedSharding(mesh, P()), state.batch_stats)
    return state.replace(
        step=NamedSharding(mesh, P()),
        params=params_sh,
        opt_state=opt_sh,
        batch_stats=bs_sh,
    )
