"""Tensor-parallel (Megatron-style) + expert + fsdp param placement for the
transformer family.

Column-parallel qkv/wi (shard the output features over tp), row-parallel
out/wo (shard the input features over tp) — XLA then inserts exactly one
all-reduce per attention/MLP block over the tp axis of the mesh (ICI).
Experts shard over ep; everything else optionally overlays fsdp on its
largest free dim. No reference counterpart: the reference operator never
touches tensors (SURVEY.md §2.10 TP row: absent).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def _overlay_fsdp(spec_list, shape, fsdp: int, min_size: int):
    from tf_operator_tpu.parallel.mesh import pick_fsdp_dim

    taken = tuple(d for d, s in enumerate(spec_list) if s is not None)
    d = pick_fsdp_dim(shape, fsdp, min_size, taken=taken)
    if d is not None:
        spec_list[d] = "fsdp"
    return spec_list


def transformer_param_sharding(
    params: Any, mesh: Mesh, min_fsdp_size: int = 2**14
) -> Any:
    """Pytree of NamedSharding matching `params` (from models/transformer.py)."""
    tp = mesh.shape.get("tp", 1)
    ep = mesh.shape.get("ep", 1)
    fsdp = mesh.shape.get("fsdp", 1)

    def place(path, x) -> NamedSharding:
        name = _path_str(path)
        shape = getattr(x, "shape", ())
        spec = [None] * len(shape)

        def ok(dim, axis_size):
            return dim < len(shape) and shape[dim] % axis_size == 0

        if tp > 1:
            if name.endswith("qkv/kernel") and ok(2, tp):
                spec[2] = "tp"  # [E, 3, H, D]: shard heads
            elif name.endswith("attn/wq/kernel") and ok(1, tp):
                spec[1] = "tp"  # llama [E, H, D]: shard query heads
            elif name.endswith("attn/wkv/kernel") and ok(2, tp):
                spec[2] = "tp"  # llama [E, 2, KV, D]: shard kv heads
            elif "attn/out/kernel" in name and ok(0, tp):
                spec[0] = "tp"  # [H, D, E]: row-parallel
            elif (name.endswith("mlp/wi/kernel") and len(shape) == 3
                    and ok(2, tp)):
                spec[2] = "tp"  # llama swiglu [E, 2, F]: column-parallel
            elif name.endswith("mlp/wi/kernel") and ok(1, tp):
                spec[1] = "tp"  # [E, F]: column-parallel
            elif name.endswith("mlp/wo/kernel") and ok(0, tp):
                spec[0] = "tp"  # [F, E]: row-parallel
            elif name.endswith("embed/embedding") and ok(0, tp):
                spec[0] = "tp"  # vocab-parallel embedding
            elif name.endswith("lm_head/kernel") and ok(1, tp):
                spec[1] = "tp"
            elif name.endswith("moe/wi") and ok(2, tp):
                spec[2] = "tp"  # [X, D, F]
            elif name.endswith("moe/wo") and ok(1, tp):
                spec[1] = "tp"  # [X, F, D]
        if ep > 1 and ("moe/wi" in name or "moe/wo" in name) and ok(0, ep):
            spec[0] = "ep"  # experts over ep
        spec = _overlay_fsdp(spec, shape, fsdp, min_fsdp_size)
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(place, params)


def state_sharding(state, mesh: Mesh, param_fn=transformer_param_sharding):
    """Sharding for a TrainState: params + mirrored opt_state, scalars
    replicated."""
    params_sh = param_fn(state.params, mesh)

    # optax states mirror the param tree where shapes match (momenta etc.);
    # shard those like their params, replicate scalars/counts
    flat_params = jax.tree.leaves_with_path(state.params)
    by_shape = {}
    for path, leaf in flat_params:
        by_shape.setdefault(getattr(leaf, "shape", ()), []).append(path)
    params_sh_flat = {tuple(p): s for p, s in jax.tree.leaves_with_path(params_sh)}

    def place_opt(path, leaf):
        shape = getattr(leaf, "shape", ())
        if not shape:
            return NamedSharding(mesh, P())
        cands = by_shape.get(shape)
        if cands:
            return params_sh_flat[tuple(cands[0])]
        return NamedSharding(mesh, P())

    opt_sh = jax.tree_util.tree_map_with_path(place_opt, state.opt_state)
    bs_sh = jax.tree.map(lambda x: NamedSharding(mesh, P()), state.batch_stats)
    return state.replace(
        step=NamedSharding(mesh, P()),
        params=params_sh,
        opt_state=opt_sh,
        batch_stats=bs_sh,
    )
