"""Device-mesh construction + logical sharding rules.

The TPU-native replacement for the reference's topology bookkeeping: where
the reference renders a TF_CONFIG peer list and lets gRPC sort it out
(reference tensorflow.go:85-139), a TPU job builds a `jax.sharding.Mesh`
over the slice and annotates arrays with logical axes; XLA inserts the
collectives, which ride ICI within a slice and DCN across slices.

Axes (any may be size 1 and is then effectively disabled):
  dcn   — cross-slice data parallel (multislice: one mesh entry per slice;
          collectives over it ride the data-center network, every other
          axis stays inside a slice on ICI)
  dp    — data parallel (batch split; gradient psum)
  fsdp  — fully-sharded data parallel (batch split + param/optimizer shard)
  tp    — tensor parallel (embed/heads/mlp split; activation collectives)
  pp    — pipeline parallel (layer stages; ppermute microbatch handoff)
  ep    — expert parallel (MoE experts split; all_to_all dispatch)
`sp` (sequence/context parallel for ring attention) reuses the `tp` axis on
the mesh — sequence shards live where attention heads live, so ring
ppermutes stay intra-slice (see ops/ring_attention.py).

dcn is OUTERMOST: jax orders devices by global process id, and the
operator's rendezvous math assigns ids slice-major (slice_id *
hosts_per_slice + host — runtime/bootstrap.py global_rendezvous), so a
contiguous reshape puts each slice's devices in one dcn row and only the
batch/gradient dp traffic crosses slices (the scaling-book recipe:
dp-over-dcn, everything else over ICI).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dcn", "pp", "dp", "fsdp", "ep", "tp")


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all). Missing axes get size 1;
    at most one axis may be -1 (inferred). Axis order puts tp innermost so
    tensor-parallel collectives map to the fastest ICI links."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {})
    sizes = {name: axes.get(name, 1) for name in AXIS_ORDER}
    infer = [k for k, v in sizes.items() if v == -1]
    if len(infer) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if infer:
        known = math.prod(v for v in sizes.values() if v != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[infer[0]] = n // known
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(
            f"mesh axes {sizes} require {total} devices, have {n}"
        )
    shape = tuple(sizes[name] for name in AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


@dataclass(frozen=True)
class MeshRules:
    """Logical-axis -> mesh-axis mapping (flax 'logical axis rules' idea,
    kept framework-free). Model code annotates arrays with logical names;
    the trainer resolves them against the active mesh."""

    rules: Tuple[Tuple[str, Union[str, Tuple[str, ...], None]], ...] = ()

    def mesh_axes(self, logical: Optional[str]):
        if logical is None:
            return None
        for name, target in self.rules:
            if name == logical:
                return target
        return None

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        return P(*(self.mesh_axes(a) for a in logical_axes))

    def with_rule(self, name: str, target) -> "MeshRules":
        kept = tuple((n, t) for n, t in self.rules if n != name)
        return MeshRules(rules=kept + ((name, target),))


DEFAULT_RULES = MeshRules(
    rules=(
        ("batch", ("dcn", "dp", "fsdp")),  # batch split over all data axes
        ("embed", "tp"),
        ("heads", "tp"),
        ("kv", None),
        ("mlp", "tp"),
        ("vocab", "tp"),
        ("seq", None),         # activations: sequence unsharded by default
        ("seq_sp", "tp"),      # ring-attention sequence sharding rides tp
        ("expert", "ep"),
        ("stage", "pp"),
        ("params_fsdp", "fsdp"),
    )
)


def named_sharding(
    mesh: Mesh, logical_axes: Sequence[Optional[str]], rules: MeshRules = DEFAULT_RULES
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes))


def batch_sharding(mesh: Mesh, rules: MeshRules = DEFAULT_RULES) -> NamedSharding:
    """Inputs: batch dim split over (dp, fsdp), rest replicated."""
    return NamedSharding(mesh, P(rules.mesh_axes("batch")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pick_fsdp_dim(
    shape: Sequence[int],
    fsdp: int,
    min_size: int = 2**14,
    taken: Sequence[int] = (),
) -> Optional[int]:
    """The single fsdp placement rule: for a param of `shape`, return the
    largest fsdp-divisible dim not already sharded (`taken`), or None for
    params below `min_size` (those replicate). Shared by the generic fsdp
    placement (runtime/train.py) and the transformer tp/ep overlay
    (parallel/tp.py) so the heuristic cannot diverge."""
    if fsdp <= 1 or not shape or math.prod(shape) < min_size:
        return None
    for d in sorted(range(len(shape)), key=lambda d: shape[d], reverse=True):
        if d not in taken and shape[d] % fsdp == 0:
            return d
    return None


def local_mesh_axes(n_devices: int, prefer_tp: int = 1) -> Dict[str, int]:
    """A reasonable default mesh for n devices: tp as requested (clamped to
    a divisor), rest data parallel."""
    tp = math.gcd(prefer_tp, n_devices) if prefer_tp > 1 else 1
    return {"tp": tp, "dp": n_devices // tp}
