"""Expert parallelism — switch routing + all-to-all dispatch over the `ep`
mesh axis.

Absent from the reference (SURVEY.md §2.10: EP row "NO"). Two dispatch
strategies exist in this framework:

  - models/transformer.py MoeMlp: dense masked-einsum dispatch, experts
    sharded over ep by GSPMD (parallel/tp.py). Zero comm code; best when
    E is small and capacity ~= tokens.
  - this module: explicit capacity-bounded all-to-all dispatch under
    shard_map — each device routes its tokens to the devices owning their
    experts (one ICI all_to_all), applies its local expert FFNs, and routes
    results back (second all_to_all). Traffic is 2 x capacity x d per
    device instead of the dense path's full [B,S,E] expansion; this is the
    scalable route for large E (Switch Transformer / GShard pattern).

All shapes static (capacity fixed up front); overflow tokens are dropped
(standard switch behavior) and their outputs are zero, so the residual
stream carries them unchanged.
"""
from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.parallel.compat import shard_map


def topk_route(
    router_logits: jax.Array, capacity: int, k: int = 1, valid=None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-expert capacity (k=1: Switch; k=2: the
    Mixtral pattern — gates renormalized over the selected experts).

    router_logits: [T, E] (float32 for a stable softmax).
    valid: optional [T] bool — False rows are PADDING (ragged batches
    rounded up to the ep axis): they consume no capacity, route nowhere,
    gate to zero, and are excluded from the aux statistics.
    Returns (dispatch [T, E, C] 0/1, combine [T, E, C] gate weights,
    aux_loss scalar).  Capacity is FIRST-CHOICE-PRIORITY: every token's
    1st-choice claim is positioned before any 2nd-choice claim (GShard
    semantics), so congestion sheds the weaker assignments first; an
    over-capacity choice is dropped (its gate weight simply vanishes —
    the residual stream carries the token unchanged for that expert).
    """
    t, n_e = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, k)                  # [T, k]
    # k=1 keeps the raw argmax prob (the Switch gate); k>1 renormalizes
    # the gates over the selected experts (the Mixtral convention)
    if k > 1:
        gates = top_p / jnp.maximum(
            top_p.sum(-1, keepdims=True), 1e-9)
    else:
        gates = top_p
    onehots = jax.nn.one_hot(top_i, n_e, dtype=jnp.int32)   # [T, k, E]
    if valid is not None:
        onehots = onehots * valid[:, None, None].astype(onehots.dtype)
    dispatch = jnp.zeros((t, n_e, capacity), router_logits.dtype)
    combine = jnp.zeros((t, n_e, capacity), router_logits.dtype)
    claimed = jnp.zeros((n_e,), jnp.int32)  # slots taken by higher choices
    for c in range(k):
        oh = onehots[:, c]                                   # [T, E]
        pos = jnp.cumsum(oh, axis=0) * oh - 1 + claimed[None, :]
        in_cap = (pos >= claimed[None, :]) & (pos < capacity) & (oh > 0)
        slot = jax.nn.one_hot(
            jnp.where(in_cap, pos, capacity), capacity + 1,
            dtype=router_logits.dtype,
        )[..., :capacity] * in_cap[..., None].astype(router_logits.dtype)
        dispatch = dispatch + slot
        combine = combine + slot * gates[:, c, None, None]
        claimed = claimed + oh.sum(axis=0)
    # aux load-balancing loss (Switch eq. 4 / Mixtral generalization):
    # density counts every top-k selection, normalized per choice
    if valid is None:
        denom = jnp.float32(t)
        probs_v = probs
    else:
        denom = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
        probs_v = probs * valid[:, None].astype(probs.dtype)
    density = jnp.sum(onehots.astype(jnp.float32), axis=(0, 1)) / (denom * k)
    router_mean = jnp.sum(probs_v, axis=0) / denom
    aux = n_e * jnp.sum(density * router_mean)
    return dispatch, combine, aux


def switch_route(
    router_logits: jax.Array, capacity: int, valid=None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing with per-expert capacity (the Switch pattern) —
    kept as the (dispatch, per-token gate, aux) view of topk_route(k=1)
    for callers that fold the gate themselves."""
    dispatch, combine, aux = topk_route(router_logits, capacity, 1, valid)
    gate = combine.sum(axis=(1, 2))  # one live slot per token -> its gate
    return dispatch, gate, aux


def _expert_ffn(h: jax.Array, act: str) -> jax.Array:
    """Post-wi nonlinearity. 'gelu': plain. 'swiglu': wi packed the gate
    and up halves on the last dim ([..., 2f] -> silu(gate) * up) — the
    LLaMA/Mixtral expert FFN."""
    if act == "swiglu":
        gate, up = jnp.split(h, 2, axis=-1)
        return jax.nn.silu(gate) * up
    return jax.nn.gelu(h)


def _local_moe(
    x: jax.Array,
    router_logits: jax.Array,
    wi: jax.Array,
    wo: jax.Array,
    valid: jax.Array,
    *,
    n_experts: int,
    capacity: int,
    axis_name: str,
    activation: str = "gelu",
    top_k: int = 1,
) -> Tuple[jax.Array, jax.Array]:
    """Per-device body under shard_map.

    x [T, d] local tokens; router_logits [T, E]; wi [E_local, d, f],
    wo [E_local, f, d] local expert weights (E_local = E / ep); valid [T]
    bool marks real (non-padding) tokens; for activation='swiglu' wi is
    [E_local, d, 2f] (gate+up packed).
    """
    ep = jax.lax.psum(1, axis_name)
    e_local = n_experts // ep
    dispatch, combine, aux = topk_route(
        router_logits.astype(jnp.float32), capacity, top_k, valid)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    # bucket local tokens by destination expert: [E, C, d]
    buckets = jnp.einsum("tec,td->ecd", dispatch, x)
    # all_to_all #1: send bucket block e to the device owning expert e.
    # [E, C, d] -> [ep, E_local, C, d] -> exchange leading dim -> on each
    # device: [ep(source), E_local(mine), C, d]
    buckets = buckets.reshape(ep, e_local, capacity, -1)
    buckets = jax.lax.all_to_all(buckets, axis_name, 0, 0, tiled=False)

    # local expert FFN over all sources at once: [ep, E_local, C, d]
    h = jnp.einsum("secd,edf->secf", buckets, wi)
    h = _expert_ffn(h, activation)
    out = jnp.einsum("secf,efd->secd", h, wo)

    # all_to_all #2: route results back to the token-owning devices
    out = jax.lax.all_to_all(out, axis_name, 0, 0, tiled=False)
    out = out.reshape(n_experts, capacity, -1)  # [E, C, d]
    # un-bucket into token order with the gate weights folded in (top-k:
    # each token sums its k expert outputs by renormalized gates)
    y = jnp.einsum("tec,ecd->td", combine, out)
    # aux is identical math on every device only if tokens were global;
    # they aren't — combine per-device values weighted by REAL token count
    # so a device holding only ragged padding does not dilute the global
    # load-balance signal (its local aux is 0 over 0 tokens)
    n_valid = valid.sum().astype(jnp.float32)
    aux = (jax.lax.psum(aux * n_valid, axis_name)
           / jnp.maximum(jax.lax.psum(n_valid, axis_name), 1.0))
    return y, aux


def make_switch_moe(
    mesh: Mesh,
    n_experts: int,
    capacity_factor: float = 1.25,
    axis_name: str = "ep",
    activation: str = "gelu",
    top_k: int = 1,
):
    """Build f(x, router_logits, wi, wo) -> (y, aux) running all-to-all EP
    over `mesh`.

    Global shapes: x [B, S, d] (batch sharded over ep), router_logits
    [B, S, E], wi [E, d, f] / wo [E, f, d] (experts sharded over ep);
    activation='swiglu' expects wi [E, d, 2f] (gate+up packed — the
    LLaMA/Mixtral expert FFN). Capacity per (device, expert) =
    ceil(local_tokens / E * factor).

    Ragged token counts are handled by PADDING up to the ep axis (the
    inference seam: a prefill's batch x prompt_len owes ep nothing):
    padding rows ride the all-to-alls as zeros, consume no expert
    capacity, are excluded from the aux statistics, and are stripped
    from the output — so expert-parallel prefill works for any shape.
    """
    ep = mesh.shape.get(axis_name, 1)
    if n_experts % ep:
        raise ValueError(f"n_experts {n_experts} not divisible by ep {ep}")
    if not 1 <= top_k <= n_experts:
        raise ValueError(f"top_k {top_k} out of range [1, {n_experts}]")

    def run(x, router_logits, wi, wo):
        b, s, d = x.shape
        t = b * s
        t_pad = -(-t // ep) * ep  # round up to the ep axis
        local_tokens = t_pad // ep
        # top-k tokens claim k slots each — capacity scales with k
        capacity = max(1, math.ceil(
            local_tokens * top_k / n_experts * capacity_factor))

        inner = functools.partial(
            _local_moe,
            n_experts=n_experts,
            capacity=capacity,
            axis_name=axis_name,
            activation=activation,
            top_k=top_k,
        )
        # flatten tokens; shard them over ep; experts already over ep
        xf = x.reshape(t, d)
        lf = router_logits.reshape(t, n_experts)
        valid = jnp.ones((t,), bool)
        if t_pad != t:
            xf = jnp.pad(xf, ((0, t_pad - t), (0, 0)))
            lf = jnp.pad(lf, ((0, t_pad - t), (0, 0)))
            valid = jnp.pad(valid, (0, t_pad - t))
        y, aux = shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(axis_name), P(axis_name), P(axis_name), P(axis_name),
                      P(axis_name)),
            out_specs=(P(axis_name), P()),
            check_rep=False,
        )(xf, lf, wi, wo, valid)
        return y[:t].reshape(b, s, d), aux

    # introspectable routing arity: model code (llama.MoeSwiGlu) checks
    # this against its own decode-path top_k so one generate() can never
    # mix top-1 prefill with top-2 decode
    run.top_k = top_k
    return run


def dense_switch_dispatch(x, router_logits, wi, wo, activation: str = "gelu",
                          dtype=None, top_k: int = 1):
    """Dense masked-einsum top-k dispatch — the zero-comm MoE path both
    model families share (transformer.MoeMlp, llama.MoeSwiGlu): every
    token through its top-k experts via one-hot einsums (capacity =
    tokens, nothing drops), Switch/Mixtral aux loss included.  top_k=1
    gates by the raw argmax prob (Switch); top_k>1 renormalizes the
    gates over the selected experts (Mixtral).  GSPMD shards the expert
    dim; best at moderate E. Returns (y [B,S,D], aux)."""
    dt = dtype or x.dtype
    n_e = wi.shape[0]
    probs = jax.nn.softmax(router_logits, axis=-1)          # [B,S,E] f32
    top_p, top_i = jax.lax.top_k(probs, top_k)              # [B,S,k]
    if top_k > 1:
        gates = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)
    else:
        gates = top_p
    onehots = jax.nn.one_hot(top_i, n_e, dtype=jnp.float32)  # [B,S,k,E]
    # per-expert gate weights (0 for unselected): [B,S,E]
    combine = jnp.einsum("bske,bsk->bse", onehots, gates).astype(dt)
    h = _expert_ffn(jnp.einsum("bsd,edf->bsef", x, wi), activation)
    out = jnp.einsum("bsef,efd->bsed", h, wo)
    out = jnp.einsum("bsed,bse->bsd", out, combine)
    # auxiliary load-balancing loss (Switch eq. 4 / Mixtral): density
    # counts every top-k selection, normalized per choice
    density = jnp.sum(onehots, axis=(0, 1, 2)) / (
        probs.shape[0] * probs.shape[1] * top_k)
    router_mean = jnp.mean(probs, axis=(0, 1))
    aux = n_e * jnp.sum(density * router_mean)
    return out, aux


def dense_reference_moe(x, router_logits, wi, wo, capacity: int,
                        activation: str = "gelu", top_k: int = 1):
    """Single-device reference with identical routing/capacity semantics —
    the correctness oracle for tests."""
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    dispatch, combine, aux = topk_route(
        router_logits.reshape(b * s, -1).astype(jnp.float32), capacity,
        top_k,
    )
    dispatch = dispatch.astype(x.dtype)
    buckets = jnp.einsum("tec,td->ecd", dispatch, xf)
    h = _expert_ffn(jnp.einsum("ecd,edf->ecf", buckets, wi), activation)
    out = jnp.einsum("ecf,efd->ecd", h, wo)
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out)
    return y.reshape(b, s, d), aux
