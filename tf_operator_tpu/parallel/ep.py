"""Expert parallelism — switch routing + all-to-all dispatch over the `ep`
mesh axis.

Absent from the reference (SURVEY.md §2.10: EP row "NO"). Two dispatch
strategies exist in this framework:

  - models/transformer.py MoeMlp: dense masked-einsum dispatch, experts
    sharded over ep by GSPMD (parallel/tp.py). Zero comm code; best when
    E is small and capacity ~= tokens.
  - this module: explicit capacity-bounded all-to-all dispatch under
    shard_map — each device routes its tokens to the devices owning their
    experts (one ICI all_to_all), applies its local expert FFNs, and routes
    results back (second all_to_all). Traffic is 2 x capacity x d per
    device instead of the dense path's full [B,S,E] expansion; this is the
    scalable route for large E (Switch Transformer / GShard pattern).

All shapes static (capacity fixed up front); overflow tokens are dropped
(standard switch behavior) and their outputs are zero, so the residual
stream carries them unchanged.
"""
from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.parallel.compat import shard_map


def switch_route(
    router_logits: jax.Array, capacity: int, valid=None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing with per-expert capacity.

    router_logits: [T, E] (float32 for a stable softmax).
    valid: optional [T] bool — False rows are PADDING (ragged batches
    rounded up to the ep axis): they consume no capacity, route nowhere,
    gate to zero, and are excluded from the aux statistics.
    Returns (dispatch [T, E, C] one-hot, gate [T], aux_loss scalar).
    Token t goes to slot `pos` of its expert's bucket, where pos is its
    order among same-expert tokens; pos >= capacity -> dropped.
    """
    t, n_e = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.max(probs, axis=-1)  # [T]
    onehot = jax.nn.one_hot(expert_idx, n_e, dtype=jnp.int32)  # [T, E]
    if valid is not None:
        onehot = onehot * valid[:, None].astype(onehot.dtype)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # [T, E]; -1 where not routed
    in_cap = (pos >= 0) & (pos < capacity)
    dispatch = jax.nn.one_hot(
        jnp.where(in_cap, pos, capacity), capacity + 1, dtype=router_logits.dtype
    )[..., :capacity] * in_cap[..., None].astype(router_logits.dtype)
    # aux load-balancing loss (Switch Transformer eq. 4) over REAL tokens
    if valid is None:
        denom = jnp.float32(t)
        probs_v = probs
    else:
        denom = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
        probs_v = probs * valid[:, None].astype(probs.dtype)
    density = jnp.sum(onehot.astype(jnp.float32), axis=0) / denom
    router_mean = jnp.sum(probs_v, axis=0) / denom
    aux = n_e * jnp.sum(density * router_mean)
    gate = gate * in_cap.any(-1).astype(gate.dtype)  # dropped tokens: zero out
    return dispatch, gate, aux


def _expert_ffn(h: jax.Array, act: str) -> jax.Array:
    """Post-wi nonlinearity. 'gelu': plain. 'swiglu': wi packed the gate
    and up halves on the last dim ([..., 2f] -> silu(gate) * up) — the
    LLaMA/Mixtral expert FFN."""
    if act == "swiglu":
        gate, up = jnp.split(h, 2, axis=-1)
        return jax.nn.silu(gate) * up
    return jax.nn.gelu(h)


def _local_moe(
    x: jax.Array,
    router_logits: jax.Array,
    wi: jax.Array,
    wo: jax.Array,
    valid: jax.Array,
    *,
    n_experts: int,
    capacity: int,
    axis_name: str,
    activation: str = "gelu",
) -> Tuple[jax.Array, jax.Array]:
    """Per-device body under shard_map.

    x [T, d] local tokens; router_logits [T, E]; wi [E_local, d, f],
    wo [E_local, f, d] local expert weights (E_local = E / ep); valid [T]
    bool marks real (non-padding) tokens; for activation='swiglu' wi is
    [E_local, d, 2f] (gate+up packed).
    """
    ep = jax.lax.psum(1, axis_name)
    e_local = n_experts // ep
    dispatch, gate, aux = switch_route(
        router_logits.astype(jnp.float32), capacity, valid)
    dispatch = dispatch.astype(x.dtype)

    # bucket local tokens by destination expert: [E, C, d]
    buckets = jnp.einsum("tec,td->ecd", dispatch, x)
    # all_to_all #1: send bucket block e to the device owning expert e.
    # [E, C, d] -> [ep, E_local, C, d] -> exchange leading dim -> on each
    # device: [ep(source), E_local(mine), C, d]
    buckets = buckets.reshape(ep, e_local, capacity, -1)
    buckets = jax.lax.all_to_all(buckets, axis_name, 0, 0, tiled=False)

    # local expert FFN over all sources at once: [ep, E_local, C, d]
    h = jnp.einsum("secd,edf->secf", buckets, wi)
    h = _expert_ffn(h, activation)
    out = jnp.einsum("secf,efd->secd", h, wo)

    # all_to_all #2: route results back to the token-owning devices
    out = jax.lax.all_to_all(out, axis_name, 0, 0, tiled=False)
    out = out.reshape(n_experts, capacity, -1)  # [E, C, d]
    # un-bucket into token order, apply gate
    y = jnp.einsum("tec,ecd->td", dispatch, out) * gate[:, None].astype(x.dtype)
    # aux is identical math on every device only if tokens were global;
    # they aren't — combine per-device values weighted by REAL token count
    # so a device holding only ragged padding does not dilute the global
    # load-balance signal (its local aux is 0 over 0 tokens)
    n_valid = valid.sum().astype(jnp.float32)
    aux = (jax.lax.psum(aux * n_valid, axis_name)
           / jnp.maximum(jax.lax.psum(n_valid, axis_name), 1.0))
    return y, aux


def make_switch_moe(
    mesh: Mesh,
    n_experts: int,
    capacity_factor: float = 1.25,
    axis_name: str = "ep",
    activation: str = "gelu",
):
    """Build f(x, router_logits, wi, wo) -> (y, aux) running all-to-all EP
    over `mesh`.

    Global shapes: x [B, S, d] (batch sharded over ep), router_logits
    [B, S, E], wi [E, d, f] / wo [E, f, d] (experts sharded over ep);
    activation='swiglu' expects wi [E, d, 2f] (gate+up packed — the
    LLaMA/Mixtral expert FFN). Capacity per (device, expert) =
    ceil(local_tokens / E * factor).

    Ragged token counts are handled by PADDING up to the ep axis (the
    inference seam: a prefill's batch x prompt_len owes ep nothing):
    padding rows ride the all-to-alls as zeros, consume no expert
    capacity, are excluded from the aux statistics, and are stripped
    from the output — so expert-parallel prefill works for any shape.
    """
    ep = mesh.shape.get(axis_name, 1)
    if n_experts % ep:
        raise ValueError(f"n_experts {n_experts} not divisible by ep {ep}")

    def run(x, router_logits, wi, wo):
        b, s, d = x.shape
        t = b * s
        t_pad = -(-t // ep) * ep  # round up to the ep axis
        local_tokens = t_pad // ep
        capacity = max(1, math.ceil(local_tokens / n_experts * capacity_factor))

        inner = functools.partial(
            _local_moe,
            n_experts=n_experts,
            capacity=capacity,
            axis_name=axis_name,
            activation=activation,
        )
        # flatten tokens; shard them over ep; experts already over ep
        xf = x.reshape(t, d)
        lf = router_logits.reshape(t, n_experts)
        valid = jnp.ones((t,), bool)
        if t_pad != t:
            xf = jnp.pad(xf, ((0, t_pad - t), (0, 0)))
            lf = jnp.pad(lf, ((0, t_pad - t), (0, 0)))
            valid = jnp.pad(valid, (0, t_pad - t))
        y, aux = shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(axis_name), P(axis_name), P(axis_name), P(axis_name),
                      P(axis_name)),
            out_specs=(P(axis_name), P()),
            check_rep=False,
        )(xf, lf, wi, wo, valid)
        return y[:t].reshape(b, s, d), aux

    return run


def dense_switch_dispatch(x, router_logits, wi, wo, activation: str = "gelu",
                          dtype=None):
    """Dense masked-einsum top-1 dispatch — the zero-comm MoE path both
    model families share (transformer.MoeMlp, llama.MoeSwiGlu): every
    token through its argmax expert via one-hot einsums (capacity =
    tokens, nothing drops), Switch aux loss included. GSPMD shards the
    expert dim; best at moderate E. Returns (y [B,S,D], aux)."""
    dt = dtype or x.dtype
    probs = jax.nn.softmax(router_logits, axis=-1)          # [B,S,E] f32
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(expert_idx, wi.shape[0], dtype=dt)
    h = _expert_ffn(jnp.einsum("bsd,edf->bsef", x, wi), activation)
    out = jnp.einsum("bsef,efd->bsed", h, wo)
    out = jnp.einsum("bsed,bse->bsd", out, onehot)
    # auxiliary load-balancing loss (Switch Transformer eq. 4)
    density = jnp.mean(onehot.astype(jnp.float32), axis=(0, 1))
    router_mean = jnp.mean(probs, axis=(0, 1))
    aux = wi.shape[0] * jnp.sum(density * router_mean)
    return out * gate[..., None].astype(dt), aux


def dense_reference_moe(x, router_logits, wi, wo, capacity: int,
                        activation: str = "gelu"):
    """Single-device reference with identical routing/capacity semantics —
    the correctness oracle for tests."""
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    dispatch, gate, aux = switch_route(
        router_logits.reshape(b * s, -1).astype(jnp.float32), capacity
    )
    dispatch = dispatch.astype(x.dtype)
    buckets = jnp.einsum("tec,td->ecd", dispatch, xf)
    h = _expert_ffn(jnp.einsum("ecd,edf->ecf", buckets, wi), activation)
    out = jnp.einsum("ecf,efd->ecd", h, wo)
    y = jnp.einsum("tec,ecd->td", dispatch, out) * gate[:, None].astype(x.dtype)
    return y.reshape(b, s, d), aux
