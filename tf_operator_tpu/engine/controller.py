"""JobEngine — the generic job-controller engine.

The equivalent of kubeflow/common's JobController.ReconcileJobs (the
top-level state machine invoked by every framework reconciler in the
reference: tfjob_controller.go:152, pytorchjob_controller.go:162,
mxjob_controller.go:177, xgboostjob_controller.go:168). Responsibilities,
in reconcile order:

  1. expectation gate (skip sync while issued creates/deletes unobserved)
  2. defaults + validation (invalid spec -> Failed condition, no pods)
  3. terminal-state handling: CleanPodPolicy teardown, TTLSecondsAfterFinished
  4. BackoffLimit / ActiveDeadlineSeconds -> job Failed
  5. gang PodGroup sync (volcano-style)
  6. per replica type: ReconcilePods (index slices, exit-code restart) +
     ReconcileServices (headless DNS identity)
  7. framework UpdateJobStatus + status write-back if changed

Deliberate fix vs the reference: ActiveDeadlineSeconds and TTL use
ReconcileResult.requeue_after instead of WorkQueue.AddAfter, which is a
silent no-op in the reference's new stack (FakeWorkQueue,
reference fake_workqueue.go:27, tfjob_controller.go:379 — SURVEY.md §7.4.6).
"""
from __future__ import annotations

import calendar
import copy
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api import common
from tf_operator_tpu.api.job import Job, ValidationError
from tf_operator_tpu.engine import metrics, tracing, warmpool
from tf_operator_tpu.engine import scheduler as cluster_scheduler
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.control import PodControl, ServiceControl
from tf_operator_tpu.engine.fanout import FanoutResult, slow_start_batch
from tf_operator_tpu.engine.expectations import (
    ControllerExpectations,
    gen_expectation_pods_key,
    gen_expectation_services_key,
)
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.fake import (
    ConflictError,
    NotFoundError,
    is_transient_api_error,
)
from tf_operator_tpu.k8s.informer import capped_exponential

# Gang-scheduling annotations (reference pod.go:223-237 / tfjob_controller.go:799-813)
GANG_GROUP_NAME_ANNOTATION = "scheduling.k8s.io/group-name"
GANG_TASK_SPEC_ANNOTATION = "volcano.sh/task-spec"
DEFAULT_GANG_SCHEDULER = "volcano"
# Second gang backend: kube-scheduler coscheduling plugin
# (scheduler-plugins).  Members join the gang via a pod LABEL naming the
# PodGroup rather than volcano's annotations, and the PodGroup lives in
# the scheduling.x-k8s.io/v1alpha1 API.  The reference snapshot is
# volcano-only; the modern training-operator supports both, selected by
# --gang-scheduler-name.
COSCHEDULING_POD_GROUP_LABEL = "scheduling.x-k8s.io/pod-group"
COSCHEDULING_SCHEDULER_NAMES = frozenset({"scheduler-plugins", "coscheduling"})
# PodGroup annotation latching which schedulingPolicy knobs the selected
# gang backend could not express (the once-per-change warning keys on it)
IGNORED_KNOBS_ANNOTATION = "kubeflow.org/ignored-scheduling-knobs"

# Event reasons (reference event vocabulary)
REASON_SUCCEEDED = "JobSucceeded"
REASON_FAILED = "JobFailed"
REASON_RUNNING = "JobRunning"
REASON_CREATED = "JobCreated"
REASON_RESTARTING = "JobRestarting"
REASON_EXITED_WITH_CODE = "ExitedWithCode"
REASON_POD_TEMPLATE_RESTART_POLICY = "SettedPodTemplateRestartPolicy"
REASON_FAILED_VALIDATION = "FailedValidation"
REASON_SUSPENDED = "JobSuspended"
REASON_RESUMED = "JobResumed"
REASON_PARTIAL_SLICE_TEARDOWN = "PartialSliceTeardown"
REASON_GANG_PENDING = "GangPending"
REASON_GANG_SCHEDULED = "GangScheduled"
# elastic resize (drain -> reshard -> resume) event/condition vocabulary
REASON_RESIZE_STARTED = "ResizeStarted"
REASON_RESIZE_ADMITTED = "ResizeAdmitted"
REASON_RESIZE_REVERTED = "ResizeReverted"
REASON_RESIZE_DRAINING = "ResizeDraining"
REASON_RESIZE_RESUMING = "ResizeResuming"
REASON_RESIZE_COMPLETED = "ResizeCompleted"

# Durable resize state: the whole drain -> reshard -> resume transition is
# crash-recoverable because every phase boundary is persisted in this
# annotation BEFORE the phase's effects begin — a mid-resize operator
# kill -9 finds the phase to finish, never a half-drained mystery.  The
# generation annotation is the cheap observable twin (monotonic int, one
# bump per started resize) that `describe`/tests can read without parsing
# the state JSON.
RESIZE_STATE_ANNOTATION = "kubeflow.org/resize-state"
RESIZE_GENERATION_ANNOTATION = "kubeflow.org/resize-generation"


class PartialSliceTeardown(RuntimeError):
    """Whole-slice restart could not delete every pod of the slice; the
    sync-level catch turns this into requeue-with-error so teardown retries
    instead of silently leaving a partially-restarted slice.  `transient`
    is True when EVERY failed delete was a client-classified transient
    error (429/5xx/reset/conflict) — an apiserver storm interrupting a
    teardown must retry on the transient ladder, not burn the bounded
    reconcile-retry budget."""

    def __init__(self, message: str, transient: bool = False) -> None:
        super().__init__(message)
        self.transient = transient


def iso_from_epoch(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def epoch_from_iso(s: str) -> float:
    return calendar.timegm(time.strptime(s, "%Y-%m-%dT%H:%M:%SZ"))


@dataclass
class EngineConfig:
    enable_gang_scheduling: bool = False
    gang_scheduler_name: str = DEFAULT_GANG_SCHEDULER
    # Crash-loop backoff for ExitCode delete-for-recreate restarts: the
    # recreation of a replica type's pods is delayed by
    #   base * 2^(restarts - free - 1)   (capped at max, +/- jitter)
    # once the persisted restart counter exceeds `free_restarts`.  The
    # first restart(s) stay immediate — a one-off preemption recovers at
    # full speed; only a *flapping* replica is slowed down.  base <= 0
    # disables the backoff entirely (the pre-hardening hot-loop behavior,
    # kept reachable for the chaos harness's regression demonstration).
    restart_backoff_base: float = 5.0
    restart_backoff_max: float = 300.0
    restart_backoff_free_restarts: int = 1
    restart_backoff_jitter: float = 0.1
    # Slow-start control fan-out cap (--control-fanout): replica pod/
    # service creates and whole-slice / scale-down deletes run in
    # exponentially growing concurrent batches (1, 2, 4, ...) capped at
    # this many in flight (engine/fanout.py).  1 (the default) is the
    # strictly serial path — ops run inline at their historical call
    # sites in the historical order, no threads — so seeded chaos runs
    # and event logs replay exactly as before the fan-out existed.
    control_fanout: int = 1
    # Elastic resize (--elastic-resize): a replica-count delta on a live
    # job becomes a failure-atomic drain -> reshard -> resume transition
    # instead of the historical scale-down-deletes + create-missing.
    # False (the default) bypasses the resize machine entirely — the
    # pre-elastic engine, byte-identical (chaos goldens untouched).
    elastic_resize: bool = False


@dataclass
class ReconcileResult:
    requeue_after: Optional[float] = None  # seconds
    error: Optional[str] = None
    # True when the error was classified transient by the client layer
    # (429/5xx/reset/conflict): the manager requeues with backoff but does
    # NOT spend the bounded reconcile-retry budget on it — an apiserver
    # outage must not exhaust a job's retries (cmd/manager.py).
    retryable: bool = False


@dataclass
class ResizeDirective:
    """What the resize state machine wants from the rest of THIS sync:
    while a resize transition is in flight (`active`) it owns gang
    admission (the normal Scheduling-condition seam is skipped) and gates
    pod creation through `may_create` — drain and a pending admit must
    not race new pods into the old shape.

    `create_within` carves out the one exception: while a resize is
    PARKED at admit (capacity shortfall, reverted to the previous
    shape), the old gang must keep FULL strength — an ExitCode
    replacement for a dying member of the still-running shape is
    allowed up to the applied shape's per-type counts (its reservation
    still exists; only target-shape growth stays blocked)."""

    active: bool = False
    may_create: bool = True
    requeue_after: Optional[float] = None
    create_within: Optional[Dict[str, int]] = None


class JobEngine:
    """One engine per job kind; shared reconcile machinery, framework
    behavior via the adapter."""

    def __init__(
        self,
        cluster,
        adapter: FrameworkAdapter,
        config: Optional[EngineConfig] = None,
        clock=time.time,
        pod_control: Optional[PodControl] = None,
        service_control: Optional[ServiceControl] = None,
        tracer: Optional[tracing.Tracer] = None,
        pod_lister=None,
        service_lister=None,
    ) -> None:
        self.cluster = cluster
        self.adapter = adapter
        self.config = config or EngineConfig()
        self.clock = clock
        # independent-replica kinds (serving fleets): replicas are
        # admitted/placed/restarted one at a time — no gang PodGroup, no
        # cluster-scheduler gang admission, and a replicas edit is a
        # plain fleet resize (the elastic drain->reshard->resume machine
        # is a gang concept; scale-in draining is the router's job,
        # engine/servefleet.py)
        self._independent = bool(
            getattr(adapter, "INDEPENDENT_REPLICAS", False)
        )
        self.tracer = tracer or tracing.get_tracer()
        # indexed informer-cache listers for the dependent kinds (wired by
        # the manager; None when the engine runs bare, e.g. unit tests).
        # When present AND synced, get_pods_for_job/get_services_for_job
        # read them instead of LISTing the apiserver — the reference's
        # steady-state read model (client-go Lister over the shared
        # informer's Indexer); absent/unsynced falls back to a live LIST
        # so correctness never depends on the cache existing.
        self.pod_lister = pod_lister
        self.service_lister = service_lister
        if clock is time.time:
            # hot path: C++ expectations (native/expectations.cc) when built;
            # a test-injected clock forces the Python implementation since the
            # native library keeps its own monotonic timestamps
            from tf_operator_tpu.native import make_expectations

            self.expectations = make_expectations()
        else:
            self.expectations = ControllerExpectations(clock=clock)
        self.pod_control = pod_control or PodControl(cluster)
        self.service_control = service_control or ServiceControl(cluster)
        # sharded control plane (engine/sharding.py): when set by the
        # manager, a callable(job_uid) -> fencing token (or None) whose
        # result is stamped into status-write bodies; the store rejects
        # stale tokens so a zombie shard can never clobber the new owner
        self.fence: Optional[Any] = None
        # expectation keys ever raised per job key — lets disown_job()
        # clear a moved job's in-flight expectations exactly (works for
        # both the Python and native ledgers, which have per-key delete
        # but no prefix scan)
        self._exp_keys: Dict[str, set] = {}
        # warm-pool pod placement (engine/warmpool.py): wired by the
        # manager when --warm-pool-size enables the pool; None keeps the
        # historical cold-create-only path byte-identical
        self.warm_pool: Optional[Any] = None
        # cluster scheduler (engine/scheduler.py): wired by the manager
        # when --scheduler-enabled builds one.  When set, pod creation is
        # gated on gang admission (the job's whole member set reserves
        # node capacity atomically or not at all) and every created pod
        # is bound to its reserved node; None bypasses every seam — the
        # pre-scheduler engine, byte-identical
        self.scheduler: Optional[Any] = None
        # job flight recorder (engine/timeline.py): wired by the manager
        # when --timeline-events-per-job > 0; one per process, shared
        # across shards.  None bypasses every recording seam.
        self.recorder: Optional[Any] = None
        # elastic-resize reshard hook: callable(job, from_shape, to_shape)
        # invoked during the resize transition's reshard phase, after the
        # gang is fully drained (final checkpoints on disk) and before any
        # pod of the new shape exists.  The operator side is deliberately
        # a seam: models/reshard.py implements the checkpoint math (load
        # at the old sharding -> host gather -> save at the new mesh's
        # shardings) and deployments wire it here; None records the phase
        # and moves on (resharding delegated to the runtime's own resume).
        # Exceptions abort the sync and retry — the phase is durable, so
        # a failed reshard re-runs instead of resuming on a stale shape.
        self.resharder: Optional[Any] = None
        # claim token -> (expectation key, job key): a warm claim raises
        # the same ledger entry a create would, and is settled by the
        # informer-delivered MODIFIED event carrying the token — exactly
        # one observation per claim, no matter how many later updates
        # touch the pod
        self._pending_claims: Dict[str, tuple] = {}
        self._claim_seq = 0
        # stale-read fence: highest resourceVersion seen or written per job
        # key.  A lagging read (apiserver watch cache, chaos-injected stale
        # window) must not drive a reconcile — acting on it deletes pods
        # and then loses the status write to a conflict, or worse, clobbers
        # newer status with old.  Numeric comparison is best-effort (k8s
        # RVs are formally opaque but etcd revisions compare in practice);
        # unparsable RVs skip the fence.
        self._rv_seen: Dict[str, str] = {}
        # informer-style hooks: observe creations/deletions for expectations
        # (reference pkg/common/util/reconciler.go:38-157)
        cluster.subscribe("Pod", self._on_pod_event)
        cluster.subscribe("Service", self._on_service_event)

    # ------------------------------------------------------------ identity
    def gen_labels(self, job_name: str) -> Dict[str, str]:
        """kubeflow/common GenLabels (used at reference tfjob_controller.go:259)."""
        return {
            objects.LABEL_GROUP_NAME: objects.GROUP_NAME,
            objects.LABEL_JOB_NAME: job_name.replace("/", "-"),
        }

    def _replica_selector(self, job: Job, rtype: str) -> str:
        """Label-selector string matching one replica type's pods (k8s
        `k=v,k=v` form; ordering fixed for stable status diffs)."""
        labels = self.gen_labels(job.name)
        labels[objects.LABEL_REPLICA_TYPE] = rtype.lower()
        return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))

    @staticmethod
    def gen_general_name(job_name: str, rtype: str, index: int) -> str:
        """{job}-{rt}-{index} naming contract (kubeflow/common GenGeneralName,
        used at reference tensorflow.go:158; asserted by the reference e2e
        suite pod_names_validation_tests.py)."""
        return f"{job_name}-{rtype.lower()}-{index}"

    # ----------------------------------------------------- crash-loop backoff
    def _restart_backoff_delay(self, job: Job, rtype: str, restarts: int) -> float:
        """Backoff imposed before recreating a type's pods after its Nth
        ExitCode restart.  Jitter is deterministic (hash of job/type/count,
        not an RNG) so reconciles are replayable: the same job history
        always produces the same schedule — which the seeded chaos soak
        depends on — while distinct jobs still decorrelate."""
        cfg = self.config
        if cfg.restart_backoff_base <= 0:
            return 0.0
        n = restarts - cfg.restart_backoff_free_restarts
        if n <= 0:
            return 0.0
        delay = capped_exponential(
            cfg.restart_backoff_base, n - 1, cfg.restart_backoff_max
        )
        frac = zlib.crc32(f"{job.key}/{rtype}/{restarts}".encode()) / 0xFFFFFFFF
        # jitter inside the cap: --restart-backoff-max is a contract, so at
        # the top of the ladder jitter only ever shortens the wait
        return min(
            cfg.restart_backoff_max,
            delay * (1.0 + cfg.restart_backoff_jitter * (2.0 * frac - 1.0)),
        )

    def _restart_backoff_remaining(
        self, job: Job, rtype: str, rs: Optional[common.ReplicaStatus]
    ) -> float:
        """Seconds left before this type may recreate pods (0 = not in
        backoff), anchored on the persisted lastRestartTime so it survives
        controller restarts."""
        if rs is None or not rs.last_restart_time or rs.restarts <= 0:
            return 0.0
        delay = self._restart_backoff_delay(job, rtype, rs.restarts)
        if delay <= 0.0:
            return 0.0
        elapsed = self.clock() - epoch_from_iso(rs.last_restart_time)
        return max(0.0, delay - elapsed)

    # ------------------------------------------------------- informer hooks
    def _expectation_key_for(self, obj: Dict[str, Any], kind: str) -> Optional[str]:
        labels = objects.labels_of(obj)
        job_name = labels.get(objects.LABEL_JOB_NAME)
        rtype = labels.get(objects.LABEL_REPLICA_TYPE)
        if not job_name or not rtype:
            return None
        job_key = f"{objects.namespace_of(obj)}/{job_name}"
        if kind == "Pod":
            return gen_expectation_pods_key(job_key, rtype)
        return gen_expectation_services_key(job_key, rtype)

    def _on_pod_event(self, event_type: str, pod: Dict[str, Any]) -> None:
        if event_type == "MODIFIED":
            # a warm-pool claim surfaces as MODIFIED, not ADDED: the pod
            # already existed (unlabeled, unowned) and the claim wrote the
            # job's identity onto it.  The claim token registered before
            # the write is popped exactly once — later updates of the same
            # pod (kubelet status writes) carry the annotation but no
            # pending entry, so they never touch the ledger.
            if self._pending_claims:
                token = (
                    (pod.get("metadata") or {}).get("annotations") or {}
                ).get(warmpool.WARM_CLAIM_ANNOTATION)
                if token:
                    entry = self._pending_claims.pop(token, None)
                    if entry is not None:
                        self.expectations.creation_observed(entry[0])
            return
        key = self._expectation_key_for(pod, "Pod")
        if key is None:
            return
        if event_type == "ADDED":
            self.expectations.creation_observed(key)
            # a relist repair after a watch outage can deliver a CLAIMED
            # pod as ADDED (the outage swallowed the claim's MODIFIED).
            # The line above just settled its expectation via the job
            # labels — retire the pending token too, or the pod's next
            # MODIFIED (any kubelet status write; the claim annotation is
            # persisted) would settle the same expectation a second time
            # and drive the ledger's add-count negative.
            if self._pending_claims:
                token = (
                    (pod.get("metadata") or {}).get("annotations") or {}
                ).get(warmpool.WARM_CLAIM_ANNOTATION)
                if token:
                    self._pending_claims.pop(token, None)
        elif event_type == "DELETED":
            self.expectations.deletion_observed(key)

    def _on_service_event(self, event_type: str, svc: Dict[str, Any]) -> None:
        key = self._expectation_key_for(svc, "Service")
        if key is None:
            return
        if event_type == "ADDED":
            self.expectations.creation_observed(key)
        elif event_type == "DELETED":
            self.expectations.deletion_observed(key)

    def satisfied_expectations(self, job: Job) -> bool:
        """AND over replica types. (The reference ORs — reconciler.go:23-35 —
        which defeats the double-creation guard whenever one replica type's
        expectations are trivially satisfied; deliberate fix.)"""
        if not job.replica_specs:
            return True
        for rtype in job.replica_specs:
            if not self.expectations.satisfied_expectations(
                gen_expectation_pods_key(job.key, rtype)
            ) or not self.expectations.satisfied_expectations(
                gen_expectation_services_key(job.key, rtype)
            ):
                return False
        return True

    # ----------------------------------------------------------- list/adopt
    def _claim_controllees(
        self, job: Job, kind: str, items: List[Dict[str, Any]]
    ) -> List[Dict[str, Any]]:
        """ControllerRefManager adopt/claim, shared by the pod and service
        paths (reference tfjob_controller.go:251-331): orphans get the
        controllerRef WRITTEN BACK (so the garbage collector reaps them with
        the job); already-owned objects are claimed strictly by UID — a
        recreated job (same name, new UID) must NOT adopt the old
        incarnation's terminating objects (reference UID recheck,
        tfjob_controller.go:277-287)."""
        can_adopt: Optional[bool] = None  # lazily computed, once per call
        claimed = []
        for item in items:
            ref = objects.get_controller_of(item)
            if ref is None:
                # never adopt a terminating orphan (client-go
                # ControllerRefManager AdoptPod precondition)
                if objects.pod_deleted(item):
                    continue
                # ... and never adopt while the job itself is being deleted;
                # the uncached recheck costs one API read, so it only runs
                # when there actually is an orphan to adopt (the reference
                # wraps it in sync.Once the same way)
                if can_adopt is None:
                    can_adopt = self._can_adopt(job)
                if not can_adopt:
                    continue
                item["metadata"].setdefault("ownerReferences", []).append(
                    objects.owner_reference(
                        {"apiVersion": job.api_version, "kind": job.kind,
                         "metadata": job.metadata}
                    )
                )
                claimed.append(self.cluster.update(kind, item))
            elif ref.get("uid") == job.uid:
                claimed.append(item)
        return claimed

    def _can_adopt(self, job: Job) -> bool:
        """reference RecheckDeletionTimestamp (tfjob_controller.go:278): a
        fresh uncached read must confirm the job is the same incarnation
        (UID) and not being deleted before any adoption happens. A missing
        job means no adoption; any other read error propagates so the sync
        aborts and retries instead of silently skipping adoption."""
        try:
            current = self.cluster.get(job.kind, job.namespace, job.name)
        except NotFoundError:
            return False
        meta = current.get("metadata", {})
        return meta.get("uid") == job.uid and not meta.get("deletionTimestamp")

    def _cached_dependents(
        self, kind: str, lister, job: Job
    ) -> Optional[List[Dict[str, Any]]]:
        """The job's dependents from the indexed informer cache, or None
        when the cache cannot serve (no lister wired / not yet synced) and
        the caller must fall back to a live LIST.  Copies are requested:
        the adopt/claim path mutates orphans (writes the controllerRef
        back), and a shared reference would corrupt the informer cache —
        FakeCluster.list has always returned isolated copies, so the
        cached path must too.  Hits and misses are counted so 'zero
        steady-state LISTs' is an assertable, observable claim."""
        if lister is None:
            metrics.CACHED_LIST_MISSES.inc({"kind": kind, "reason": "no_lister"})
            return None
        if not lister.synced():
            metrics.CACHED_LIST_MISSES.inc({"kind": kind, "reason": "not_synced"})
            return None
        items = lister.list(
            namespace=job.namespace, selector=self.gen_labels(job.name),
            copy=True,
        )
        metrics.CACHED_LIST_HITS.inc({"kind": kind})
        return items

    def get_pods_for_job(self, job: Job) -> List[Dict[str, Any]]:
        """List by GenLabels selector — from the indexed informer cache in
        steady state, live LIST as the correctness fallback — then
        adopt/claim (reference tfjob_controller.go:251-290).  Adoption
        semantics are unchanged either way: the uncached UID recheck
        (_can_adopt) still guards every orphan claim, and stale-cache
        writes surface as conflicts that retry the sync on fresh state."""
        pods = self._cached_dependents("Pod", self.pod_lister, job)
        if pods is None:
            pods = self.cluster.list_pods(
                namespace=job.namespace, selector=self.gen_labels(job.name)
            )
        return self._claim_controllees(job, "Pod", pods)

    def get_services_for_job(self, job: Job) -> List[Dict[str, Any]]:
        """Service twin of get_pods_for_job (reference
        ServiceControllerRefManager, tfjob_controller.go:295-331)."""
        svcs = self._cached_dependents("Service", self.service_lister, job)
        if svcs is None:
            svcs = self.cluster.list_services(
                namespace=job.namespace, selector=self.gen_labels(job.name)
            )
        return self._claim_controllees(job, "Service", svcs)

    @staticmethod
    def filter_for_replica_type(
        items: List[Dict[str, Any]], rtype: str
    ) -> List[Dict[str, Any]]:
        """kubeflow/common FilterPodsForReplicaType (reference pod.go:87)."""
        rt = rtype.lower()
        return [
            it
            for it in items
            if objects.labels_of(it).get(objects.LABEL_REPLICA_TYPE) == rt
        ]

    @staticmethod
    def get_slices(
        items: List[Dict[str, Any]], replicas: int
    ) -> List[List[Dict[str, Any]]]:
        """Index-bucketed slices sized max(replicas, highest index + 1) so the
        caller can create missing indices and delete out-of-range ones
        (kubeflow/common GetPodSlices contract, reference pod.go:98-127)."""
        size = replicas
        parsed = []
        for it in items:
            try:
                idx = int(objects.labels_of(it).get(objects.LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            parsed.append((idx, it))
            size = max(size, idx + 1)
        slices: List[List[Dict[str, Any]]] = [[] for _ in range(size)]
        for idx, it in parsed:
            if idx >= 0:
                slices[idx].append(it)
        return slices

    # ------------------------------------------------------------ reconcile
    def reconcile(self, job: Job, corr_id: Optional[int] = None) -> ReconcileResult:
        """Full ReconcileJobs state machine. Mutates job.status and writes it
        back to the cluster if changed. The whole sync runs under a root
        span; each phase below opens a child span that also feeds the
        per-phase histogram, so one instrumentation point serves both the
        trace timeline and Prometheus.

        `corr_id` is the workqueue's correlation id (stamped at enqueue,
        threaded through the manager's dispatch): it rides the root span
        and the flight recorder's sync bridge, so a timeline reads
        "enqueued (corr 17) → waited 1.2s → sync (corr 17) spent 40ms in
        pod_reconcile" as one causal chain."""
        attrs: Dict[str, Any] = {"kind": self.adapter.KIND, "job": job.key}
        if corr_id is not None:
            attrs["corr"] = corr_id
        root: Optional[tracing.Span] = None
        try:
            with self.tracer.span("reconcile", attrs=attrs) as root:
                return self._reconcile(job)
        finally:
            # bridge the finished span tree into the job's timeline (the
            # finally runs after the span closed, so duration is set);
            # a sync that RAISED still lands — the storm that aborted it
            # belongs in the story
            if self.recorder is not None and root is not None:
                self.recorder.record_sync(
                    job.key, root, corr=corr_id, uid=job.uid
                )

    def _phase(self, name: str, **attrs):
        """Child span for one sync phase, feeding
        tpu_operator_sync_phase_duration_seconds{kind,phase}."""
        return self.tracer.span(
            name,
            attrs={"kind": self.adapter.KIND, **attrs},
            histogram=metrics.SYNC_PHASE_DURATION,
            labels={"kind": self.adapter.KIND, "phase": name},
        )

    @staticmethod
    def _rv_int(rv: Optional[str]) -> Optional[int]:
        try:
            return int(rv)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            return None

    def _fence_stale_read(self, job: Job) -> bool:
        """True when this job object is OLDER than state this engine has
        already seen or written — the sync must be retried on a fresh read
        instead of acting on (and then writing back) stale state."""
        rv = self._rv_int((job.metadata or {}).get("resourceVersion"))
        if rv is None:
            return False
        seen = self._rv_int(self._rv_seen.get(job.key))
        if seen is not None and seen > rv:
            return True
        self._rv_seen[job.key] = str(rv)
        return False

    def forget_job(self, job_key: str) -> None:
        """Drop per-job engine memory (fence watermark + tracked
        expectation keys) once the job is gone; a recreated job starts a
        fresh incarnation.  The expectation records themselves are already
        settled by the deletion path — only the key-tracking set must not
        outlive the job (it would grow with lifetime job count)."""
        self._rv_seen.pop(job_key, None)
        self._exp_keys.pop(job_key, None)
        self._drop_pending_claims(job_key)
        if self.recorder is not None:
            # the job is GONE (not moved — disown_job handles moves and
            # must NOT touch the shared recorder): its timeline keeps
            # serving reads but becomes LRU-evictable
            self.recorder.finish(job_key)
        if self.scheduler is not None:
            # a deleted job's reservation (or pending entry) must not hold
            # capacity — release by key: the UID died with the object
            self.scheduler.release_key(job_key)

    def _track_exp_key(self, job_key: str, key: str) -> None:
        self._exp_keys.setdefault(job_key, set()).add(key)

    def _drop_pending_claims(self, job_key: str) -> None:
        for token in [
            t for t, (_k, jk) in list(self._pending_claims.items())
            if jk == job_key
        ]:
            self._pending_claims.pop(token, None)

    def disown_job(self, job_key: str) -> None:
        """The job moved to another shard (slot failover / resize): drop
        every piece of per-job engine state so nothing leaks and nothing
        stale gates the NEW owner's syncs if the slot ever comes back —
        in-flight expectations are deleted (rebuilt from scratch by
        whoever owns the job next), the rv watermark is cleared."""
        for key in self._exp_keys.pop(job_key, ()):
            self.expectations.delete_expectations(key)
        self._rv_seen.pop(job_key, None)
        self._drop_pending_claims(job_key)

    def _reconcile(self, job: Job) -> ReconcileResult:
        if self._fence_stale_read(job):
            return ReconcileResult(
                error=f"stale read of {job.key} "
                f"(rv {job.metadata.get('resourceVersion')!r} older than "
                f"last seen); requeueing for a fresh read",
                requeue_after=1.0,
                retryable=True,
            )
        now_iso = iso_from_epoch(self.clock())
        status = job.status
        old_status = copy.deepcopy(status)

        # Created condition on first contact (reference onOwnerCreateFunc /
        # addTFJob set Created; job.go:59-138)
        if not status.conditions:
            common.update_job_conditions(
                status, common.JOB_CREATED, REASON_CREATED,
                f"{self.adapter.KIND} {job.name} is created.", now_iso,
            )
            self.cluster.record_event(
                job.to_dict(), "Normal", REASON_CREATED,
                f"{self.adapter.KIND} {job.name} is created.",
            )
            metrics.JOBS_CREATED.inc({"job_namespace": job.namespace})

        # validation: invalid spec -> Failed condition, no pods (reference
        # e2e invalid_tfjob_tests.py; legacy job.go:40-56 writes Failed)
        try:
            self.adapter.set_defaults(job)
            self.adapter.validate(job)
        except ValidationError as e:
            common.update_job_conditions(
                status, common.JOB_FAILED, REASON_FAILED_VALIDATION, str(e), now_iso
            )
            self.cluster.record_event(
                job.to_dict(), "Warning", REASON_FAILED_VALIDATION, str(e)
            )
            self._write_status(job, old_status)
            return ReconcileResult(error=str(e))

        # expectation gate (reference tfjob_controller.go:139-146)
        with self._phase("expectation_check"):
            satisfied = self.satisfied_expectations(job)
        if not satisfied:
            return ReconcileResult()

        # ONE dependents read per sync: this snapshot is threaded through
        # every consumer below (per-type reconcile, whole-slice teardown,
        # the framework status rules) — re-listing inside the sync bought
        # nothing but API round trips, and under cached listers a re-list
        # could even be a LAGGING view of what this sync just did
        with self._phase("dependents_list"):
            pods = self.get_pods_for_job(job)
            services = self.get_services_for_job(job)
        replicas = job.replica_specs

        # ----- terminal state: clean pods, TTL (reference ReconcileJobs head)
        if common.is_finished(status):
            metrics.RUNNING_REPLICAS_TRACKER.forget(self.adapter.KIND, job.key)
            self._delete_pods_and_services(job, pods, services)
            if self.config.enable_gang_scheduling:
                self._delete_pod_group(job)
            if self.scheduler is not None:
                self.scheduler.release(job.uid)
            res = self._cleanup_job_ttl(job)
            self._write_status(job, old_status)
            return res

        # ----- suspend/resume (modern training-operator semantics; no
        # reference counterpart — the snapshot predates RunPolicy.suspend).
        # Suspend tears down every pod/service and PodGroup, stamps the
        # Suspended condition, and resets StartTime so the
        # ActiveDeadlineSeconds clock restarts on resume (batch/v1 Job
        # suspend behavior).
        if job.run_policy.suspend:
            metrics.RUNNING_REPLICAS_TRACKER.forget(self.adapter.KIND, job.key)
            self._delete_pods_and_services(job, pods, services, force_all=True)
            if self.config.enable_gang_scheduling:
                self._delete_pod_group(job)
            if self.scheduler is not None:
                # a suspended gang holds no capacity; resume re-admits
                self.scheduler.release(job.uid)
            # counts describe live pods only; the ExitCode restart counter is
            # history and survives suspension, and the selector must too —
            # /scale's labelSelectorPath reads it while suspended
            for rtype in replicas:
                prev = status.replica_statuses.get(rtype)
                status.replica_statuses[rtype] = common.ReplicaStatus(
                    restarts=prev.restarts if prev else 0,
                    selector=self._replica_selector(job, rtype),
                    last_restart_time=prev.last_restart_time if prev else None,
                )
            if not common.is_suspended(status):
                msg = f"{self.adapter.KIND} {job.name} is suspended."
                self.cluster.record_event(
                    job.to_dict(), "Normal", REASON_SUSPENDED, msg
                )
                common.update_job_conditions(
                    status, common.JOB_SUSPENDED, REASON_SUSPENDED, msg, now_iso
                )
            status.start_time = None
            self._write_status(job, old_status)
            return ReconcileResult()
        if common.is_suspended(status):
            msg = f"{self.adapter.KIND} {job.name} is resumed."
            self.cluster.record_event(job.to_dict(), "Normal", REASON_RESUMED, msg)
            common.demote_condition(
                status, common.JOB_SUSPENDED, now_iso,
                reason=REASON_RESUMED, message=msg,
            )

        # ----- BackoffLimit / ActiveDeadlineSeconds -> Failed
        failure_message = None
        if self._past_backoff_limit(job, pods):
            failure_message = (
                f"{self.adapter.KIND} {job.name} has failed because it has "
                f"reached the specified backoff limit"
            )
        elif self._past_active_deadline(job):
            failure_message = (
                f"{self.adapter.KIND} {job.name} has failed because it was "
                f"active longer than specified deadline"
            )
        if failure_message is not None:
            metrics.RUNNING_REPLICAS_TRACKER.forget(self.adapter.KIND, job.key)
            if status.completion_time is None:
                status.completion_time = now_iso
            self._delete_pods_and_services(job, pods, services, force_all=True)
            if self.config.enable_gang_scheduling:
                self._delete_pod_group(job)
            if self.scheduler is not None:
                self.scheduler.release(job.uid)
            self.cluster.record_event(
                job.to_dict(), "Normal", REASON_FAILED, failure_message
            )
            common.update_job_conditions(
                status, common.JOB_FAILED, REASON_FAILED, failure_message, now_iso
            )
            metrics.JOBS_FAILED.inc({"job_namespace": job.namespace})
            self._write_status(job, old_status)
            return ReconcileResult()

        # ----- gang PodGroup sync (independent-replica kinds never gang)
        if self.config.enable_gang_scheduling and not self._independent:
            with self._phase("gang_sync"):
                self._sync_pod_group(job)

        # ----- elastic resize (drain -> reshard -> resume): when enabled,
        # a replica-count delta against the durably recorded applied shape
        # enters (or continues) the failure-atomic resize transition.
        # While a transition is in flight it OWNS gang admission and the
        # may-create gate; any phase error requeues with the phase state
        # untouched on the API server — the next sync finishes it.
        resize = None
        if self.config.elastic_resize and not self._independent:
            try:
                with self._phase("resize"):
                    resize = self._sync_resize(job, status, pods, now_iso)
            except Exception as e:  # noqa: BLE001 — requeue like pod errors
                self._write_status(job, old_status)
                return ReconcileResult(
                    error=str(e), requeue_after=1.0,
                    retryable=(
                        is_transient_api_error(e)
                        or getattr(e, "transient", False)
                    ),
                )
        resize_owns = resize is not None and resize.active

        # ----- cluster-scheduler gang admission (engine/scheduler.py):
        # the job's whole member set reserves node capacity atomically or
        # not at all.  Admission gates CREATION only — deletes, exit-code
        # restarts, and status counting below still run for an unadmitted
        # job (a preempted gang must finish its delete-for-recreate and
        # keep exact restart counters while it waits for capacity).
        gang_admitted = True
        if resize_owns:
            gang_admitted = resize.may_create
        elif self.scheduler is not None and not self._independent:
            with self._phase("gang_admission"):
                gang_admitted = self._sync_gang_admission(
                    job, status, pods, now_iso
                )

        # ----- per replica type: pods + services. API errors (e.g. 409 on a
        # name held by a dying pod of an older incarnation) abort this sync
        # with an error result — controller-runtime style requeue-on-error —
        # rather than crashing the loop.  Transient errors (429/5xx/reset/
        # conflict) are flagged retryable so the manager's bounded retry
        # budget is not spent on them.
        restarted_types: set = set()
        requeue_candidates: List[float] = []
        create_within = resize.create_within if resize_owns else None
        try:
            for rtype, spec in replicas.items():
                with self._phase("pod_reconcile", replica_type=rtype):
                    backoff_left = self.reconcile_pods(
                        job, status, pods, rtype, spec, replicas, now_iso,
                        restarted_types, may_create=gang_admitted,
                        create_within=create_within,
                    )
                if backoff_left:
                    requeue_candidates.append(backoff_left)
                with self._phase("service_reconcile", replica_type=rtype):
                    self.reconcile_services(job, services, rtype, spec)
        except Exception as e:  # noqa: BLE001 — any API failure requeues
            self._write_status(job, old_status)
            return ReconcileResult(
                error=str(e), requeue_after=1.0,
                retryable=(
                    is_transient_api_error(e) or getattr(e, "transient", False)
                ),
            )

        # ----- framework status rules
        if status.start_time is None:
            status.start_time = now_iso
        with self._phase("status_update"):
            # the sync-start snapshot, NOT a fresh list: the replica counts
            # the rules read were computed from this same snapshot, so a
            # re-list could only disagree with them (and costs a LIST)
            ctx = StatusContext(
                replicas, status,
                pods, now_iso,
                lambda etype, reason, msg: self.cluster.record_event(
                    job.to_dict(), etype, reason, msg
                ),
                restarted_types=restarted_types,
            )
            self.adapter.update_job_status(self, job, ctx)
        status.last_reconcile_time = now_iso
        metrics.RUNNING_REPLICAS_TRACKER.update(
            self.adapter.KIND, job.key,
            {rt: status.replica_statuses[rt].active
             for rt in replicas if rt in status.replica_statuses},
        )

        with self._phase("status_write"):
            self._write_status(job, old_status)

        # requeue for ActiveDeadlineSeconds (RequeueAfter fix, SURVEY §7.4.6)
        # and for pending crash-loop backoff windows — the soonest wakeup
        # wins so neither deadline nor delayed recreation relies on an
        # unrelated event arriving.
        ads = job.run_policy.active_deadline_seconds
        if ads is not None and status.start_time is not None:
            remaining = epoch_from_iso(status.start_time) + ads - self.clock()
            requeue_candidates.append(max(0.0, remaining))
        if resize is not None and resize.requeue_after is not None:
            # mid-transition: the resize machine drives its own cadence
            requeue_candidates.append(resize.requeue_after)
        if not gang_admitted and not resize_owns:
            # pending gang: retry admission without waiting for the next
            # object event (capacity frees when other gangs finish)
            requeue_candidates.append(self.scheduler.retry_interval)
        requeue = min(requeue_candidates) if requeue_candidates else None
        return ReconcileResult(requeue_after=requeue)

    # -------------------------------------------------------- gang admission
    def _gang_members(self, job: Job) -> Dict[str, int]:
        """The gang: every replica pod name the current spec implies,
        mapped to its chip demand (slice shape of its type's template —
        the same annotation the warm pool routes on)."""
        members: Dict[str, int] = {}
        for rtype, spec in (job.replica_specs or {}).items():
            chips = cluster_scheduler.chips_of_shape(
                warmpool.slice_shape_of(spec.template)
            )
            for index in range(spec.replicas or 0):
                members[self.gen_general_name(job.name, rtype, index)] = chips
        return members

    def _existing_placements(
        self, members: Dict[str, int], pods: List[Dict[str, Any]]
    ) -> tuple:
        """(existing, pod_names) for admission: live pods' placements —
        physical reality admission adopts verbatim — and the actual pod
        name of members served by a warm claim (the standby's name)."""
        existing: Dict[str, str] = {}
        pod_names: Dict[str, str] = {}
        for pod in pods:
            ann = (pod.get("metadata") or {}).get("annotations") or {}
            # a warm-claimed pod keeps its standby NAME — the member
            # identity the gang knows it by rides the late-binding
            # annotation (filtering on the pod name would orphan the
            # member from its own reservation)
            member = (
                ann.get(warmpool.WARM_BOUND_NAME_ANNOTATION)
                or objects.name_of(pod)
            )
            if member not in members or not objects.is_pod_active(pod):
                continue
            if member != objects.name_of(pod):
                pod_names[member] = objects.name_of(pod)
            node = ann.get(
                cluster_scheduler.ASSIGNED_NODE_ANNOTATION
            ) or objects.pod_node(pod)
            if node:
                existing[member] = node
        return existing, pod_names

    def _admit_gang(
        self, job: Job, pods: List[Dict[str, Any]],
        members: Optional[Dict[str, int]] = None,
    ) -> tuple:
        """One fit-checked admission attempt for the job's spec-derived
        gang.  Shared by the normal Scheduling seam and the resize
        machine's admit/resume phases; (True, "") without a scheduler.
        `members` lets a caller that already computed the gang reuse it."""
        if self.scheduler is None:
            return True, ""
        if members is None:
            members = self._gang_members(job)
        existing, pod_names = self._existing_placements(members, pods)
        return self.scheduler.admit(
            job_key=job.key,
            job_uid=job.uid,
            kind=self.adapter.KIND,
            namespace=job.namespace,
            members=members,
            priority=cluster_scheduler.priority_of(job),
            existing=existing,
            throughput=cluster_scheduler.throughput_ratios_of(job),
            pod_names=pod_names,
            min_replicas=cluster_scheduler.min_replicas_of(job),
        )

    def _sync_gang_admission(
        self,
        job: Job,
        status: common.JobStatus,
        pods: List[Dict[str, Any]],
        now_iso: str,
    ) -> bool:
        """Admit (or re-assert) the job's gang with the cluster scheduler.
        Live pods' placements are handed in as `existing` so admission
        adopts physical reality (restart resync, warm-claimed pods on
        standby nodes) instead of re-placing anything.  Not-admitted
        stamps the Scheduling condition + a GangPending event (once per
        message change); admission clears it with a GangScheduled event."""
        members = self._gang_members(job)
        admitted, msg = self._admit_gang(job, pods, members=members)
        prev = common.get_condition(status, common.JOB_SCHEDULING)
        if admitted:
            if prev is not None and prev.status == "True":
                done = f"gang admitted: {len(members)} replica(s) bound"
                common.demote_condition(
                    status, common.JOB_SCHEDULING, now_iso,
                    reason=REASON_GANG_SCHEDULED, message=done,
                )
                self.cluster.record_event(
                    job.to_dict(), "Normal", REASON_GANG_SCHEDULED, done
                )
            return True
        # the event fires once per pending transition or message change,
        # not once per sync — a gang parked for an hour is one event, but
        # a shortfall that changes shape is worth a fresh line
        if prev is None or prev.status != "True" or prev.message != msg:
            self.cluster.record_event(
                job.to_dict(), "Normal", REASON_GANG_PENDING, msg
            )
            # once per pending transition or shortfall change, like the
            # event — the timeline carries the chip-shortfall math, not
            # one line per parked sync
            if self.recorder is not None:
                self.recorder.record(
                    job.key, "scheduler", "gang_pending",
                    {"message": msg}, uid=job.uid,
                )
        common.update_job_conditions(
            status, common.JOB_SCHEDULING, REASON_GANG_PENDING, msg, now_iso
        )
        return False

    # --------------------------------------------------------- elastic resize
    @staticmethod
    def _spec_shape(job: Job) -> Dict[str, int]:
        return {
            rt: (spec.replicas or 0)
            for rt, spec in (job.replica_specs or {}).items()
        }

    @staticmethod
    def _shape_str(shape: Optional[Dict[str, int]]) -> str:
        return ",".join(f"{rt}={n}" for rt, n in sorted((shape or {}).items()))

    def _resize_state(self, job: Job) -> Optional[Dict[str, Any]]:
        """The durable resize state (phase machine position) from the
        job's annotation, or None when never stamped."""
        import json as _json

        ann = (job.metadata or {}).get("annotations") or {}
        raw = ann.get(RESIZE_STATE_ANNOTATION)
        if not raw:
            return None
        try:
            state = _json.loads(raw)
        except ValueError:
            return None
        return state if isinstance(state, dict) else None

    def _write_resize_state(self, job: Job, state: Dict[str, Any]) -> bool:
        """Persist the resize state annotation on the job CR — the ONE
        durable record every phase transition goes through BEFORE its
        effects begin, so a mid-resize operator crash (kill -9, chaos)
        re-enters exactly the phase it left.  One conflict retry on fresh
        state; other errors propagate (the sync requeues, the previous
        phase stays durable).  The in-hand job's metadata is refreshed
        from the write so the sync's own status write-back does not
        conflict with it."""
        import json as _json

        payload = _json.dumps(state, separators=(",", ":"), sort_keys=True)
        gen = str(state.get("gen", 0))
        for attempt in (0, 1):
            try:
                current = self.cluster.get(
                    self.adapter.KIND, job.namespace, job.name
                )
            except NotFoundError:
                return False
            ann = current.setdefault("metadata", {}).setdefault(
                "annotations", {}
            )
            ann[RESIZE_STATE_ANNOTATION] = payload
            ann[RESIZE_GENERATION_ANNOTATION] = gen
            try:
                written = self.cluster.update(self.adapter.KIND, current)
            except ConflictError:
                if attempt == 1:
                    raise
                continue
            md = written.get("metadata", {}) or {}
            job.metadata.setdefault("annotations", {}).update(
                md.get("annotations") or {}
            )
            rv = md.get("resourceVersion")
            if self._rv_int(rv) is not None:
                job.metadata["resourceVersion"] = rv
                self._rv_seen[job.key] = rv
            return True
        return False

    def _record_resize(self, job: Job, event: str,
                       detail: Dict[str, Any]) -> None:
        """DECISIONS-ring record for a resize milestone (resize_requested
        / drained / resharded / resumed / reverted)."""
        if self.recorder is not None:
            self.recorder.record(
                job.key, "controller", event, detail, uid=job.uid
            )

    def _sync_resize(
        self,
        job: Job,
        status: common.JobStatus,
        pods: List[Dict[str, Any]],
        now_iso: str,
    ) -> Optional[ResizeDirective]:
        """The failure-atomic resize phase machine.  Phases (durable in
        the resize-state annotation, advanced strictly forward):

          done   — no transition in flight; a spec shape differing from
                   the recorded applied shape STARTS one (gen+1, admit)
          admit  — fit-check the target shape through the scheduler's
                   atomic resize path.  Failure reverts: the reservation
                   is already restored to the old full shape, no pod has
                   been touched, and the gang keeps running while the
                   admit retries (ResizeReverted, once per message)
          drain  — gracefully delete the gang's in-range active pods
                   (kubelet SIGTERM -> runtime/loop.py's guard lands one
                   final checkpoint); out-of-range pods ride the normal
                   scale-down path in the same sync.  Advances only when
                   NO dependent pod remains
          reshard— run the wired resharder (checkpoint: old sharding ->
                   host gather -> new mesh shardings; models/reshard.py)
                   exactly between "fully drained" and "first new pod"
          resume — re-assert admission (an operator restarted mid-resize
                   rebuilds the reservation here), let creation proceed,
                   and complete once every target replica is Running

        Every phase is re-entrant: the sync that finds phase P finishes
        P's remaining work and only then persists P+1."""
        spec_shape = self._spec_shape(job)
        state = self._resize_state(job)
        if state is None:
            # first contact under --elastic-resize: durably record the
            # applied shape so later spec edits are a detectable delta
            self._write_resize_state(
                job, {"gen": 0, "phase": "done", "to": spec_shape}
            )
            return None
        phase = state.get("phase", "done")
        if phase == "done":
            if state.get("to") == spec_shape:
                # steady state — but finish a completion whose status
                # write was lost after the annotation landed (crash
                # between the two): the condition must not stay True.
                # A CANCELLED transition repairs as a revert, not a
                # completion — the resize-duration SLO must never
                # observe a transition that disrupted nothing.
                if common.is_resizing(status):
                    if state.get("cancelled"):
                        self._finish_cancel(job, status, state, now_iso)
                    else:
                        self._finish_resize(job, status, state, now_iso)
                return None
            state = {
                "gen": int(state.get("gen", 0)) + 1,
                "phase": "admit",
                "from": dict(state.get("to") or {}),
                "to": spec_shape,
                "t0": round(self.clock(), 3),
            }
            self._write_resize_state(job, state)
            msg = (
                f"resize {self._shape_str(state['from'])} -> "
                f"{self._shape_str(spec_shape)} requested"
            )
            self.cluster.record_event(
                job.to_dict(), "Normal", REASON_RESIZE_STARTED, msg
            )
            self._record_resize(
                job, "resize_requested",
                {"gen": state["gen"], "from": state["from"],
                 "to": spec_shape},
            )
            common.update_job_conditions(
                status, common.JOB_RESIZING, REASON_RESIZE_STARTED, msg,
                now_iso,
            )
            phase = "admit"
        elif state.get("to") != spec_shape:
            if (
                state.get("phase") == "admit"
                and spec_shape == (state.get("from") or {})
            ):
                # the spec moved BACK to the applied shape before any
                # drain happened (a cancelled resize, or the scheduler's
                # shrink request racing a user revert): nothing was
                # disrupted — end the transition instead of pointlessly
                # bouncing the whole gang through drain -> resume.  The
                # durable `cancelled` marker is what lets a crash
                # between this write and the status write repair as a
                # REVERT (done-branch above), not a phantom completion.
                state = {
                    "gen": int(state.get("gen", 0)), "phase": "done",
                    "to": spec_shape, "cancelled": True,
                }
                self._write_resize_state(job, state)
                self._finish_cancel(job, status, state, now_iso)
                return None
            # the spec moved again mid-transition: restart at admit with
            # the new target (drained pods stay drained; a completed
            # reshard is re-run against the new shape)
            state = {
                "gen": int(state.get("gen", 0)) + 1,
                "phase": "admit",
                "from": dict(state.get("from") or {}),
                "to": spec_shape,
                "t0": state.get("t0", round(self.clock(), 3)),
            }
            self._write_resize_state(job, state)
            msg = (
                f"resize retargeted to {self._shape_str(spec_shape)} "
                f"mid-transition"
            )
            self.cluster.record_event(
                job.to_dict(), "Normal", REASON_RESIZE_STARTED, msg
            )
            self._record_resize(
                job, "resize_requested",
                {"gen": state["gen"], "from": state["from"],
                 "to": spec_shape},
            )
            phase = "admit"

        target = {rt: int(n) for rt, n in (state.get("to") or {}).items()}

        if phase == "admit":
            admitted, why = self._admit_gang(job, pods)
            if not admitted:
                # the scheduler's atomic restore already put the old full
                # reservation back; nothing was drained — the gang keeps
                # running at the previous shape while the admit retries
                msg = (
                    f"resize to {self._shape_str(target)} reverted to "
                    f"previous shape: {why}"
                )
                prev = common.get_condition(status, common.JOB_RESIZING)
                if (
                    prev is None or prev.status != "True"
                    or prev.reason != REASON_RESIZE_REVERTED
                    or prev.message != msg
                ):
                    self.cluster.record_event(
                        job.to_dict(), "Normal", REASON_RESIZE_REVERTED, msg
                    )
                    self._record_resize(
                        job, "reverted",
                        {"gen": state.get("gen"), "why": why},
                    )
                common.update_job_conditions(
                    status, common.JOB_RESIZING, REASON_RESIZE_REVERTED,
                    msg, now_iso,
                )
                retry = (
                    self.scheduler.retry_interval
                    if self.scheduler is not None else 5.0
                )
                return ResizeDirective(
                    active=True, may_create=False, requeue_after=retry,
                    # the gang keeps running at the previous shape — and
                    # keeps REPAIRING at it: ExitCode replacements within
                    # the applied shape stay allowed (their members'
                    # reservations survived the atomic restore)
                    create_within=dict(state.get("from") or {}),
                )
            msg = f"resize to {self._shape_str(target)} admitted; draining"
            state = {**state, "phase": "drain"}
            self._write_resize_state(job, state)
            self.cluster.record_event(
                job.to_dict(), "Normal", REASON_RESIZE_ADMITTED, msg
            )
            common.update_job_conditions(
                status, common.JOB_RESIZING, REASON_RESIZE_ADMITTED, msg,
                now_iso,
            )
            phase = "drain"

        if phase == "drain":
            if not pods:
                state = {**state, "phase": "reshard"}
                self._write_resize_state(job, state)
                self._record_resize(job, "drained", {"gen": state.get("gen")})
                phase = "reshard"
            else:
                drained = self._drain_for_resize(job, pods, target)
                common.update_job_conditions(
                    status, common.JOB_RESIZING, REASON_RESIZE_DRAINING,
                    f"draining {drained} pod(s) for the final checkpoint",
                    now_iso,
                )
                return ResizeDirective(
                    active=True, may_create=False, requeue_after=1.0
                )

        if phase == "reshard":
            if self.resharder is not None:
                # raises propagate: the phase is durable, a failed
                # reshard re-runs — resuming on a stale shape is the one
                # outcome this phase exists to prevent
                self.resharder(job, dict(state.get("from") or {}), target)
            state = {**state, "phase": "resume"}
            self._write_resize_state(job, state)
            self._record_resize(job, "resharded", {"gen": state.get("gen")})
            phase = "resume"

        if phase == "resume":
            admitted, why = self._admit_gang(job, pods)
            if not admitted:
                # capacity was stolen while the gang was down (operator
                # restart mid-resize, a higher-priority arrival): park
                # creation exactly like a pending gang, keep the phase
                common.update_job_conditions(
                    status, common.JOB_RESIZING, REASON_RESIZE_RESUMING,
                    f"waiting to resume at {self._shape_str(target)}: "
                    f"{why}",
                    now_iso,
                )
                retry = (
                    self.scheduler.retry_interval
                    if self.scheduler is not None else 5.0
                )
                return ResizeDirective(
                    active=True, may_create=False, requeue_after=retry
                )
            running: Dict[str, int] = {}
            for pod in pods:
                rt = objects.labels_of(pod).get(objects.LABEL_REPLICA_TYPE)
                if rt and objects.pod_phase(pod) == objects.POD_RUNNING:
                    running[rt] = running.get(rt, 0) + 1
            complete = all(
                running.get(rt.lower(), 0) == n for rt, n in target.items()
            )
            if not complete:
                common.update_job_conditions(
                    status, common.JOB_RESIZING, REASON_RESIZE_RESUMING,
                    f"resuming at {self._shape_str(target)}", now_iso,
                )
                return ResizeDirective(
                    active=True, may_create=True, requeue_after=1.0
                )
            state = {
                "gen": state.get("gen"), "phase": "done", "to": target,
                "t0": state.get("t0"),
            }
            self._write_resize_state(job, state)
            self._finish_resize(job, status, state, now_iso)
            return None

        return None

    def _finish_cancel(
        self, job: Job, status: common.JobStatus, state: Dict[str, Any],
        now_iso: str,
    ) -> None:
        """End a cancelled-before-drain transition: final `reverted`
        record (the timeline closes its resize clock WITHOUT observing a
        duration), ResizeReverted event, condition demoted.  Shared by
        the cancel branch and the done-branch crash repair."""
        msg = (
            f"resize cancelled before drain; running at "
            f"{self._shape_str(state.get('to'))}"
        )
        self.cluster.record_event(
            job.to_dict(), "Normal", REASON_RESIZE_REVERTED, msg
        )
        self._record_resize(
            job, "reverted", {"gen": state.get("gen"), "final": True}
        )
        common.demote_condition(
            status, common.JOB_RESIZING, now_iso,
            reason=REASON_RESIZE_REVERTED, message=msg,
        )

    def _finish_resize(
        self, job: Job, status: common.JobStatus, state: Dict[str, Any],
        now_iso: str,
    ) -> None:
        """Demote the Resizing condition and stamp the resumed milestone
        (also the repair path for a completion whose status write was
        lost after the annotation landed)."""
        t0 = state.get("t0")
        detail: Dict[str, Any] = {"gen": state.get("gen")}
        if isinstance(t0, (int, float)):
            detail["duration"] = round(max(0.0, self.clock() - t0), 3)
        self._record_resize(job, "resumed", detail)
        msg = (
            f"resize to {self._shape_str(state.get('to'))} complete; "
            f"resumed from the resharded checkpoint"
        )
        common.demote_condition(
            status, common.JOB_RESIZING, now_iso,
            reason=REASON_RESIZE_COMPLETED, message=msg,
        )
        self.cluster.record_event(
            job.to_dict(), "Normal", REASON_RESIZE_COMPLETED, msg
        )

    def _drain_for_resize(
        self, job: Job, pods: List[Dict[str, Any]], target: Dict[str, int]
    ) -> int:
        """Gracefully delete the gang's pods for the resize: the
        kubelet's SIGTERM gives runtime/loop.py's signal guard its final
        checkpoint.  Ownership split, so the drain-complete check
        (`no dependent pods remain`) can always be reached:

          - out-of-range pods of SPEC'd types ride the per-type loops'
            historical scale-down delete in this same sync;
          - Failed pods whose type is ExitCode with a retryable code
            belong to the restart machinery (deleting them here would
            swallow the restart-counter increment the chaos accounting
            cross-checks);
          - EVERYTHING else — active in-range pods, in-range Succeeded
            pods, pods of types no longer in the spec, unparsable
            indices — is drained here: no other path ever deletes them,
            and one leftover would wedge the phase machine in drain
            forever.

        Returns deletes issued."""
        lower_target = {rt.lower(): n for rt, n in target.items()}
        specs_by_lower = {
            rt.lower(): (rt, spec)
            for rt, spec in (job.replica_specs or {}).items()
        }
        n = 0
        for pod in pods:
            labels = objects.labels_of(pod)
            rt = labels.get(objects.LABEL_REPLICA_TYPE) or ""
            try:
                idx: Optional[int] = int(
                    labels.get(objects.LABEL_REPLICA_INDEX, "")
                )
            except ValueError:
                idx = None
            if (
                rt in specs_by_lower and idx is not None
                and idx >= lower_target.get(rt, 0)
            ):
                continue  # out-of-range: the scale-down path owns it
            rtype, spec = specs_by_lower.get(rt, (rt or "worker", None))
            if objects.pod_phase(pod) == objects.POD_FAILED:
                exit_code = objects.container_exit_code(
                    pod, self.adapter.CONTAINER_NAME
                )
                if (
                    spec is not None
                    and spec.restart_policy == common.RESTART_POLICY_EXIT_CODE
                    and common.is_retryable_exit_code(exit_code)
                ):
                    # the ExitCode machinery deletes AND counts this one
                    continue
                # permanent failures were already visible to this sync's
                # status rules (same snapshot); non-ExitCode policies
                # have no delete path of their own — drain it
            self._delete_pod_with_expectations(job, rtype, pod)
            n += 1
        return n

    # ------------------------------------------------------------- pods
    def reconcile_pods(
        self,
        job: Job,
        status: common.JobStatus,
        pods: List[Dict[str, Any]],
        rtype: str,
        spec: common.ReplicaSpec,
        replicas: Dict[str, common.ReplicaSpec],
        now_iso: str,
        restarted_types: Optional[set] = None,
        may_create: bool = True,
        create_within: Optional[Dict[str, int]] = None,
    ) -> Optional[float]:
        """Per-replica-type pod reconciliation: create missing indices, delete
        out-of-range (dynamic scale down), exit-code restart handling, replica
        status counting (reference tfjob_controller.go:644-740). Types whose
        pods were deleted-for-restart this sync are added to
        `restarted_types` for the status rules.

        `may_create=False` (gang not admitted by the cluster scheduler)
        skips ONLY the create-missing-pod branch: deletes, restarts, and
        counting run regardless, so a capacity-starved job still converges
        its teardown half and keeps exact restart accounting.

        `create_within` (a resize parked at admit) re-opens creation for
        indices below the APPLIED shape's per-type count even while
        may_create is False: the running gang keeps repairing itself at
        the old shape; only target-shape growth stays blocked.

        Returns the remaining crash-loop backoff when pod creation was
        deferred by it (the caller requeues for that instant), else None."""
        typed = self.filter_for_replica_type(pods, rtype)
        num_replicas = spec.replicas or 0
        # initializeReplicaStatuses (reference status.go:244-249) — the
        # persisted ExitCode restart counter survives the per-sync reset so
        # BackoffLimit can count delete-for-recreate restarts; the selector
        # feeds the /scale subresource's labelSelectorPath (HPA); the
        # lastRestartTime anchor survives so the crash-loop backoff keeps
        # its place across syncs and controller restarts
        prev = status.replica_statuses.get(rtype)
        backoff_left = self._restart_backoff_remaining(job, rtype, prev)
        status.replica_statuses[rtype] = common.ReplicaStatus(
            restarts=prev.restarts if prev else 0,
            selector=self._replica_selector(job, rtype),
            last_restart_time=prev.last_restart_time if prev else None,
        )
        restarted_this_pass = False
        creation_deferred = False
        creations = 0
        # indices of CREATE ops within pending_ops (fan-out mode): the
        # dispatch result reports failures by op index, so the timeline
        # can count exactly how many creates actually succeeded
        create_indices: set = set()
        # control fan-out: at fanout > 1 creates and scale-down/stale-gen
        # deletes are COLLECTED during the scan and dispatched afterwards in
        # slow-start batches; at fanout <= 1 `pending_ops` stays None and
        # every op runs inline at its historical call site — the exact
        # pre-fan-out order the seeded chaos logs replay
        pending_ops: Optional[List] = (
            [] if self.config.control_fanout > 1 else None
        )

        slices = self.get_slices(typed, num_replicas)
        for index, pod_slice in enumerate(slices):
            if len(pod_slice) > 1:
                continue  # too many pods for index; wait for deletion to settle
            if len(pod_slice) == 0:
                if not may_create and (
                    create_within is None
                    or index >= create_within.get(rtype, 0)
                ):
                    # gang not admitted: the scheduler holds no capacity
                    # for this member yet — creation waits (the sync-level
                    # requeue retries admission), everything else proceeds
                    continue
                if backoff_left > 0.0:
                    # mid-backoff after a delete-for-recreate: a flapping
                    # replica must not hot-loop pod churn — recreation waits
                    # out the window, surfaced to the caller as requeue_after
                    creation_deferred = True
                    continue
                master_role = self.adapter.is_master_role(replicas, rtype, index)
                if pending_ops is not None:
                    create_indices.add(len(pending_ops))
                self._run_or_defer(
                    pending_ops,
                    lambda i=index, m=master_role: self._create_new_pod(
                        job, rtype, i, spec, m, replicas
                    ),
                )
                creations += 1
                continue
            pod = pod_slice[0]
            if index < 0 or index >= num_replicas:
                # out-of-range: scale down (reference tfjob_controller.go:698-703)
                self._run_or_defer(
                    pending_ops,
                    lambda p=pod: self._delete_pod_with_expectations(
                        job, rtype, p
                    ),
                )
                continue

            gen = objects.pod_restart_generation(pod)
            if (
                getattr(self.adapter, "WHOLE_SLICE_RESTART", False)
                and gen is not None
                and gen < status.replica_statuses[rtype].restarts
            ):
                # stale incarnation: an earlier whole-slice teardown was
                # interrupted (PartialSliceTeardown) — finish it instead of
                # absorbing a pre-restart pod into the recreated slice
                self._run_or_defer(
                    pending_ops,
                    lambda p=pod: self._delete_pod_with_expectations(
                        job, rtype, p
                    ),
                )
                if restarted_types is not None:
                    restarted_types.add(rtype)
                continue

            exit_code = objects.container_exit_code(pod, self.adapter.CONTAINER_NAME)
            if exit_code != 0xBEEF and objects.pod_phase(pod) == objects.POD_FAILED:
                self.cluster.record_event(
                    job.to_dict(), "Normal", REASON_EXITED_WITH_CODE,
                    f"Pod: {objects.namespace_of(pod)}.{objects.name_of(pod)} "
                    f"exited with code {exit_code}",
                )
            if (
                spec.restart_policy == common.RESTART_POLICY_EXIT_CODE
                and objects.pod_phase(pod) == objects.POD_FAILED
                and common.is_retryable_exit_code(exit_code)
            ):
                # delete-for-recreate + Restarting condition
                # (reference tfjob_controller.go:705-736).  NEVER deferred
                # to the fan-out: the restart-counter increment just below
                # must only happen once this delete has succeeded — a
                # deferred failure after the increment would persist a
                # phantom restart through the sync-level status write
                self._delete_pod_with_expectations(job, rtype, pod)
                msg = (
                    f"{self.adapter.KIND} {job.name} is restarting because "
                    f"{rtype} replica(s) failed."
                )
                self.cluster.record_event(
                    job.to_dict(), "Warning", REASON_RESTARTING, msg
                )
                common.update_job_conditions(
                    status, common.JOB_RESTARTING, REASON_RESTARTING, msg, now_iso
                )
                metrics.JOBS_RESTARTED.inc({"job_namespace": job.namespace})
                rs = status.replica_statuses[rtype]
                rs.restarts += 1
                # anchor the crash-loop backoff on this restart; the applied
                # delay is observed by _write_status once the increment is
                # DURABLY persisted — observing here would double-count the
                # same restart whenever the delete or status write fails and
                # the sync retries
                rs.last_restart_time = now_iso
                restarted_this_pass = True
                if restarted_types is not None:
                    restarted_types.add(rtype)
                continue

            # updateJobReplicaStatuses (reference status.go:253-262)
            phase = objects.pod_phase(pod)
            rs = status.replica_statuses[rtype]
            if phase == objects.POD_RUNNING:
                rs.active += 1
            elif phase == objects.POD_SUCCEEDED:
                rs.succeeded += 1
            elif phase == objects.POD_FAILED:
                rs.failed += 1

        # dispatch the deferred creates / scale-down deletes (fanout > 1
        # only) in slow-start batches; the first failure aborts the ramp
        # and surfaces exactly like the serial path's first exception —
        # each op raised/lowered its own expectations, and never-attempted
        # ops never touched them, so the accounting stays exact
        if pending_ops:
            res = self._dispatch_control_ops(pending_ops)
            self._record_fanout(job, "Pod", rtype, res)
            # record BEFORE raise_first: pods created by the batch exist
            # even when a sibling op failed, and a milestone skipped here
            # would never be re-stamped (the next sync sees the pods and
            # counts zero creations).  n counts creates that actually
            # SUCCEEDED — ops dispatch in list order, so an op ran iff
            # its index < attempted, and succeeded iff it is not among
            # the failures; a batch whose every create died must not
            # stamp the "scheduled" milestone for pods that don't exist.
            if creations and self.recorder is not None:
                failed_idx = {i for i, _e in res.failures}
                created_ok = sum(
                    1 for i in create_indices
                    if i < res.attempted and i not in failed_idx
                )
                if created_ok:
                    self.recorder.record(
                        job.key, "controller", "pods_created",
                        {"replica_type": rtype, "n": created_ok,
                         "failed_ops": len(res.failures)},
                        uid=job.uid,
                    )
            res.raise_first()
        elif creations and self.recorder is not None:
            # serial mode: a failing create raised out of the loop above,
            # so reaching here means every counted create succeeded — the
            # "scheduled" milestone without a cluster scheduler
            # (placement and creation coincide; with one, gang_admitted
            # lands first and wins)
            self.recorder.record(
                job.key, "controller", "pods_created",
                {"replica_type": rtype, "n": creations}, uid=job.uid,
            )
        if creation_deferred and self.recorder is not None:
            self.recorder.record(
                job.key, "controller", "restart_backoff",
                {"replica_type": rtype, "wait": round(backoff_left, 3)},
                uid=job.uid,
            )

        # Whole-slice gang restart: a TPU slice is unusable partially, so a
        # retryable failure tears down ALL replicas of the type for atomic
        # recreation (SURVEY.md §5.3/§7.4.1 — no reference counterpart; the
        # reference restarts pods individually).
        if restarted_this_pass and getattr(self.adapter, "WHOLE_SLICE_RESTART", False):
            # the sync's own snapshot (`typed`), not a re-list: pods already
            # deleted above answer NotFound (counted as success by
            # _delete_pod_with_expectations), and a pod CREATED earlier in
            # this same pass carries the pre-restart generation label, so
            # the stale-incarnation sweep deletes it on the next sync — the
            # same repair path that finishes any interrupted teardown.
            # abort_on_failure=False: every delete is attempted even after
            # failures — one stuck pod must not leave the others running —
            # then the partial teardown surfaces loudly below
            teardown_names: List[str] = []
            teardown_ops: List = []
            for pod_slice in self.get_slices(typed, num_replicas):
                for pod in pod_slice:
                    teardown_names.append(objects.name_of(pod))
                    teardown_ops.append(
                        lambda p=pod: self._delete_pod_with_expectations(
                            job, rtype, p
                        )
                    )
            res = slow_start_batch(
                teardown_ops, self.config.control_fanout,
                abort_on_failure=False,
            )
            failed_deletes = [teardown_names[i] for i, _ in res.failures]
            all_transient = all(
                is_transient_api_error(e) for _, e in res.failures
            )
            # counts no longer reflect reality; reset for this pass (the
            # restart counter is history, not a count of live pods — keep it;
            # the selector feeds /scale's labelSelectorPath — keep it too;
            # lastRestartTime anchors the backoff — keep it)
            status.replica_statuses[rtype] = common.ReplicaStatus(
                restarts=status.replica_statuses[rtype].restarts,
                selector=self._replica_selector(job, rtype),
                last_restart_time=status.replica_statuses[rtype].last_restart_time,
            )
            if failed_deletes:
                # A partially-torn-down slice is exactly the state whole-slice
                # restart exists to prevent: event + raise so the sync-level
                # catch requeues-with-error and retries the teardown.
                msg = (
                    f"{self.adapter.KIND} {job.name} whole-slice restart "
                    f"could not delete {rtype} pod(s) "
                    f"{', '.join(failed_deletes)}; slice teardown is partial"
                )
                self.cluster.record_event(
                    job.to_dict(), "Warning", REASON_PARTIAL_SLICE_TEARDOWN, msg
                )
                raise PartialSliceTeardown(msg, transient=all_transient)
        return backoff_left if creation_deferred else None

    def _delete_pod_with_expectations(self, job: Job, rtype: str, pod) -> None:
        """Expectation-guarded pod delete, shared by scale-down, exit-code
        restart, stale-incarnation cleanup, and whole-slice teardown.
        NotFound counts as success — the pod is already gone (deleted
        earlier this sync, or the list came from a lagging cache) — but the
        deletion will never surface as an informer event, so the
        expectation is settled here."""
        key = gen_expectation_pods_key(job.key, rtype)
        self._track_exp_key(job.key, key)
        self.expectations.raise_expectations(key, 0, 1)
        try:
            self.pod_control.delete_pod(
                job.namespace, objects.name_of(pod), job.to_dict()
            )
        except NotFoundError:
            self.expectations.lower_expectations(key, 0, 1)
        except Exception:
            self.expectations.lower_expectations(key, 0, 1)
            raise

    def _create_new_pod(
        self,
        job: Job,
        rtype: str,
        index: int,
        spec: common.ReplicaSpec,
        master_role: bool,
        replicas: Dict[str, common.ReplicaSpec],
    ) -> None:
        """reference createNewPod (tfjob_controller.go:744-834)."""
        rt = rtype.lower()
        key = gen_expectation_pods_key(job.key, rtype)
        self._track_exp_key(job.key, key)
        self.expectations.raise_expectations(key, 1, 0)

        labels = self.gen_labels(job.name)
        labels[objects.LABEL_REPLICA_TYPE] = rt
        labels[objects.LABEL_REPLICA_INDEX] = str(index)
        if master_role:
            labels[objects.LABEL_JOB_ROLE] = "master"
        if getattr(self.adapter, "WHOLE_SLICE_RESTART", False):
            # incarnation stamp: lets later syncs finish an interrupted
            # whole-slice teardown (a stale-generation pod is deleted on
            # sight instead of being absorbed into the recreated slice)
            rs = job.status.replica_statuses.get(rtype)
            labels[objects.LABEL_RESTART_GENERATION] = str(
                rs.restarts if rs else 0
            )

        template = copy.deepcopy(spec.template)
        meta = template.setdefault("metadata", {})
        meta["name"] = self.gen_general_name(job.name, rtype, index)
        meta.setdefault("labels", {}).update(labels)

        self.adapter.set_cluster_spec(job, template, rtype, index)

        # pod-template restart policy is overridden by the replica-level one;
        # warn like the reference (tfjob_controller.go:788-794)
        if template.get("spec", {}).get("restartPolicy"):
            self.cluster.record_event(
                job.to_dict(), "Warning", REASON_POD_TEMPLATE_RESTART_POLICY,
                "Restart policy in pod template will be overwritten by restart "
                "policy in replica spec",
            )
        # ExitCode is operator-implemented: pod itself must not be restarted
        # by kubelet (reference setRestartPolicy, pod.go:321-328)
        if spec.restart_policy == common.RESTART_POLICY_EXIT_CODE:
            template.setdefault("spec", {})["restartPolicy"] = common.RESTART_POLICY_NEVER
        else:
            template.setdefault("spec", {})["restartPolicy"] = spec.restart_policy

        if self.config.enable_gang_scheduling and not self._independent:
            user_scheduler = template.get("spec", {}).get("schedulerName")
            if not user_scheduler:
                template["spec"]["schedulerName"] = self.config.gang_scheduler_name
            elif user_scheduler != self.config.gang_scheduler_name:
                self.cluster.record_event(
                    job.to_dict(), "Warning", "PodTemplateSchedulerName",
                    "Another scheduler is specified when gang-scheduling is "
                    "enabled and it will not be overwritten",
                )
            if self._gang_coscheduling():
                # coscheduling joins members to the gang by label
                meta.setdefault("labels", {})[
                    COSCHEDULING_POD_GROUP_LABEL] = job.name
            else:
                annotations = meta.setdefault("annotations", {})
                annotations[GANG_GROUP_NAME_ANNOTATION] = job.name
                annotations[GANG_TASK_SPEC_ANNOTATION] = rt

        controller_ref = objects.owner_reference(
            {"apiVersion": job.api_version, "kind": job.kind, "metadata": job.metadata}
        )
        # cluster scheduler: the member was reserved a node at gang
        # admission — bind the pod to it at create (spec.nodeName) and
        # stamp the reservation into an annotation so a restarted
        # operator's resync rebuilds placements from the pods themselves
        planned_node = None
        if self.scheduler is not None:
            planned_node = self.scheduler.planned_node(job.uid, meta["name"])
            if planned_node is not None:
                meta.setdefault("annotations", {})[
                    cluster_scheduler.ASSIGNED_NODE_ANNOTATION
                ] = planned_node
                template.setdefault("spec", {})["nodeName"] = planned_node
        # warm-pool fast path: claim a pre-provisioned standby pod of the
        # template's slice shape before paying a cold create.  The claim
        # reuses the expectation raised above (settled by the claim's own
        # MODIFIED event); a miss falls straight through to the cold
        # create with the ledger untouched in between.  The reserved node
        # rides along as a speculative placement hint: a standby already
        # sitting on the gang's node is preferred, and a claim that lands
        # elsewhere rebinds the reservation to where the pod really is.
        if self.warm_pool is not None and self._claim_warm_pod(
            job, rtype, index, template, dict(meta.get("labels", {})), key,
            controller_ref, node_hint=planned_node,
        ):
            return
        try:
            self.pod_control.create_pod_with_controller_ref(
                job.namespace, template, job.to_dict(), controller_ref
            )
        except Exception:
            # creation failed: the informer won't observe it — lower the
            # expectation (reference tfjob_controller.go:824-832)
            self.expectations.creation_observed(key)
            raise

    def _claim_warm_pod(
        self,
        job: Job,
        rtype: str,
        index: int,
        template: Dict[str, Any],
        labels: Dict[str, str],
        exp_key: str,
        controller_ref: Dict[str, Any],
        node_hint: Optional[str] = None,
    ) -> bool:
        """Try to serve this replica from the warm pool.  Returns True when
        a standby pod was claimed (the replica exists; no create needed).

        Ledger contract: the caller already raised the creation
        expectation.  The claim token is registered BEFORE the CAS write,
        so the claim's MODIFIED event — delivered synchronously by the
        fake store, or later by a real watch — observes it exactly once;
        a miss pops the token and leaves the raised expectation for the
        cold create's ADDED to settle; an error lowers it and propagates
        (a fenced claim surfaces as the store's 403, which
        _sync_guarded's fenced-mid-sync handling already owns)."""
        import json as _json

        self._claim_seq += 1
        token = f"{job.uid}/{rtype}/{index}/{self._claim_seq}"
        spec = template.get("spec", {}) or {}
        container = (spec.get("containers") or [{}])[0]
        annotations = {
            warmpool.WARM_CLAIM_ANNOTATION: token,
            # the identity + env the pod would have carried cold-created:
            # the late-binding contract the pre-warmed runtime reads
            warmpool.WARM_BOUND_NAME_ANNOTATION: template["metadata"]["name"],
        }
        env = container.get("env") or []
        if env:
            annotations[warmpool.WARM_BOUND_ENV_ANNOTATION] = _json.dumps(
                env, separators=(",", ":"), sort_keys=True
            )
        fence_token = self.fence(job.uid) if self.fence is not None else None
        self._pending_claims[token] = (exp_key, job.key)
        try:
            claimed = self.warm_pool.try_claim(
                namespace=job.namespace,
                shape=warmpool.slice_shape_of(template),
                image=container.get("image", ""),
                labels=labels,
                annotations=annotations,
                controller_ref=controller_ref,
                fence_token=fence_token,
                # the EFFECTIVE policy (_new_pod already rewrote ExitCode
                # to Never): pod spec is immutable, so only a policy-equal
                # standby may serve this replica
                restart_policy=spec.get("restartPolicy"),
                # speculative placement: prefer a standby already sitting
                # on the gang's reserved node (scheduler hint); any ready
                # standby still beats a cold create
                node_hint=node_hint,
            )
        except Exception:
            # the claim write failed terminally (e.g. fenced): no event
            # will ever carry the token — settle the ledger here, exactly
            # like a failed create
            self._pending_claims.pop(token, None)
            self.expectations.creation_observed(exp_key)
            raise
        if claimed is None:
            self._pending_claims.pop(token, None)
            return False
        if self.scheduler is not None:
            # the standby's immutable spec pinned its node (and its
            # NAME): move the member's reservation to where the pod
            # physically runs, and record the actual pod name so
            # eviction/drain kill the pod that exists
            self.scheduler.rebind(
                job.uid, template["metadata"]["name"],
                objects.pod_node(claimed) or "",
                pod_name=objects.name_of(claimed),
            )
        self.cluster.record_event(
            job.to_dict(), "Normal", "WarmPodClaimed",
            f"claimed warm pod {objects.namespace_of(claimed)}."
            f"{objects.name_of(claimed)} for {rtype} replica {index}",
        )
        return True

    # ------------------------------------------------------------- services
    @staticmethod
    def _run_or_defer(pending_ops: Optional[List], op) -> None:
        """The one place the fan-out dispatch decision lives: serial mode
        (pending_ops is None) runs the thunk inline at its historical call
        site; fan-out mode defers it for the slow-start batch.  Callers
        must pass a thunk that owns its captures (default-arg lambda) —
        late-binding a loop variable would make every deferred op act on
        the last iteration's object."""
        if pending_ops is None:
            op()
        else:
            pending_ops.append(op)

    def _dispatch_control_ops(
        self, ops: List, abort_on_failure: bool = True
    ) -> FanoutResult:
        """Run deferred control ops through the slow-start fan-out (only the
        fanout > 1 paths defer; the serial engine never builds an op list)."""
        return slow_start_batch(
            ops, self.config.control_fanout, abort_on_failure=abort_on_failure
        )

    def _record_fanout(self, job: Job, kind: str, rtype: str,
                       res: FanoutResult) -> None:
        """Timeline record for one slow-start batch dispatch — outcomes
        included, so an aborted ramp mid-storm is visible per job."""
        if self.recorder is None:
            return
        self.recorder.record(
            job.key, "fanout", "batch",
            {"kind": kind, "replica_type": rtype, "ops": res.attempted,
             "failed": len(res.failures)},
            uid=job.uid,
        )

    def reconcile_services(
        self,
        job: Job,
        services: List[Dict[str, Any]],
        rtype: str,
        spec: common.ReplicaSpec,
    ) -> None:
        """One headless Service per replica index — the stable DNS identity
        peers dial ({job}-{rt}-{i}.{ns}.svc, reference tensorflow.go:153-166;
        engine ReconcileServices).  Creates and scale-down deletes ride the
        same slow-start fan-out as pods (inline and strictly ordered at
        fanout <= 1)."""
        typed = self.filter_for_replica_type(services, rtype)
        num_replicas = spec.replicas or 0
        slices = self.get_slices(typed, num_replicas)
        pending_ops: Optional[List] = (
            [] if self.config.control_fanout > 1 else None
        )
        for index, svc_slice in enumerate(slices):
            if len(svc_slice) > 1:
                continue
            if len(svc_slice) == 0:
                self._run_or_defer(
                    pending_ops,
                    lambda i=index: self._create_new_service(
                        job, rtype, i, spec
                    ),
                )
            else:
                svc = svc_slice[0]
                if index >= num_replicas:
                    self._run_or_defer(
                        pending_ops,
                        lambda s=svc:
                        self._delete_service_with_expectations(job, rtype, s),
                    )
        if pending_ops:
            res = self._dispatch_control_ops(pending_ops)
            self._record_fanout(job, "Service", rtype, res)
            res.raise_first()

    def _delete_service_with_expectations(
        self, job: Job, rtype: str, svc: Dict[str, Any]
    ) -> None:
        """Expectation-guarded service delete (scale-down path)."""
        key = gen_expectation_services_key(job.key, rtype)
        self._track_exp_key(job.key, key)
        self.expectations.raise_expectations(key, 0, 1)
        try:
            self.service_control.delete_service(
                job.namespace, objects.name_of(svc), job.to_dict()
            )
        except Exception:
            self.expectations.lower_expectations(key, 0, 1)
            raise

    def _create_new_service(
        self, job: Job, rtype: str, index: int, spec: common.ReplicaSpec
    ) -> None:
        rt = rtype.lower()
        key = gen_expectation_services_key(job.key, rtype)
        self._track_exp_key(job.key, key)
        self.expectations.raise_expectations(key, 1, 0)

        labels = self.gen_labels(job.name)
        labels[objects.LABEL_REPLICA_TYPE] = rt
        labels[objects.LABEL_REPLICA_INDEX] = str(index)

        port = self._replica_port(spec)
        svc = objects.make_service(
            name=self.gen_general_name(job.name, rtype, index),
            namespace=job.namespace,
            labels=labels,
            selector=labels,
            port=port,
            port_name=self.adapter.PORT_NAME,
        )
        controller_ref = objects.owner_reference(
            {"apiVersion": job.api_version, "kind": job.kind, "metadata": job.metadata}
        )
        try:
            self.service_control.create_service_with_controller_ref(
                job.namespace, svc, job.to_dict(), controller_ref
            )
        except Exception:
            self.expectations.creation_observed(key)
            raise

    def _replica_port(self, spec: common.ReplicaSpec) -> int:
        return objects.replica_port(
            spec.template,
            self.adapter.CONTAINER_NAME,
            self.adapter.PORT_NAME,
            self.adapter.DEFAULT_PORT,
        )

    # ----------------------------------------------------------- run policy
    def _delete_pods_and_services(
        self,
        job: Job,
        pods: List[Dict[str, Any]],
        services: Optional[List[Dict[str, Any]]] = None,
        force_all: bool = False,
    ) -> None:
        """kubeflow/common DeletePodsAndServices: CleanPodPolicy None keeps
        everything; Running deletes only still-running pods; All deletes all.
        Service shares the pod's name.  The listed services drive deletion
        too: a service left behind by a swallowed earlier delete error must
        not outlive its (already gone) pod — with force_all every listed
        service goes; otherwise only pod-less orphans."""
        policy = job.run_policy.clean_pod_policy or common.CLEAN_POD_POLICY_RUNNING
        if not force_all and policy == common.CLEAN_POD_POLICY_NONE:
            return
        # whole-slice teardown rides the slow-start fan-out too: every op
        # swallows its own errors (teardown is best-effort and re-driven by
        # the next sync), so abort_on_failure=False and the serial path is
        # byte-identical to the historical per-pod loop
        ops: List = []
        for pod in pods:
            if (
                not force_all
                and policy == common.CLEAN_POD_POLICY_RUNNING
                and objects.pod_phase(pod) != objects.POD_RUNNING
            ):
                continue
            ops.append(
                lambda n=objects.name_of(pod):
                self._delete_pod_and_service_quietly(job, n)
            )
        # orphan services: a pod-less service (earlier swallowed delete
        # error) is always cleaned; services whose pod exists were already
        # handled alongside the pod above (or deliberately kept by policy)
        pod_names = {objects.name_of(p) for p in pods}
        for svc in services or []:
            name = objects.name_of(svc)
            if name in pod_names:
                continue
            ops.append(
                lambda n=name: self._delete_service_quietly(job, n)
            )
        slow_start_batch(
            ops, self.config.control_fanout, abort_on_failure=False
        )

    def _delete_pod_and_service_quietly(self, job: Job, name: str) -> None:
        try:
            self.pod_control.delete_pod(job.namespace, name, job.to_dict())
        except Exception:
            pass
        self._delete_service_quietly(job, name)

    def _delete_service_quietly(self, job: Job, name: str) -> None:
        try:
            self.service_control.delete_service(
                job.namespace, name, job.to_dict()
            )
        except Exception:
            pass

    def _cleanup_job_ttl(self, job: Job) -> ReconcileResult:
        """TTLSecondsAfterFinished: delete the job CR once expired, else
        requeue for the remainder."""
        ttl = job.run_policy.ttl_seconds_after_finished
        if ttl is None:
            return ReconcileResult()
        finish = job.status.completion_time
        if finish is None:
            return ReconcileResult()
        expire_at = epoch_from_iso(finish) + ttl
        remaining = expire_at - self.clock()
        if remaining <= 0:
            try:
                # jobs_deleted_total is counted by the manager's informer
                # delete handler so user deletes and TTL deletes tally once
                self.cluster.delete(self.adapter.KIND, job.namespace, job.name)
            except Exception:
                pass
            return ReconcileResult()
        return ReconcileResult(requeue_after=remaining)

    def _past_active_deadline(self, job: Job) -> bool:
        ads = job.run_policy.active_deadline_seconds
        if ads is None or job.status.start_time is None:
            return False
        return self.clock() - epoch_from_iso(job.status.start_time) >= ads

    def _past_backoff_limit(self, job: Job, pods: List[Dict[str, Any]]) -> bool:
        """kubeflow/common PastBackoffLimit, extended: kubelet restart counts
        of running pods for OnFailure/Always types, PLUS the persisted
        operator restart counter for ExitCode types.  The reference counts
        only the former, so ExitCode delete-for-recreate restarts (fresh pod,
        restartCount=0) loop forever — the default failure mode for TPUJob,
        whose replicas default to ExitCode (api/tpujob.py)."""
        limit = job.run_policy.backoff_limit
        if limit is None:
            return False
        total = 0
        for rtype, spec in (job.replica_specs or {}).items():
            if spec.restart_policy == common.RESTART_POLICY_EXIT_CODE:
                rs = job.status.replica_statuses.get(rtype)
                if rs is not None:
                    total += rs.restarts
                continue
            if spec.restart_policy not in (
                common.RESTART_POLICY_ON_FAILURE,
                common.RESTART_POLICY_ALWAYS,
            ):
                continue
            for pod in self.filter_for_replica_type(pods, rtype):
                if objects.pod_phase(pod) != objects.POD_RUNNING:
                    continue
                for cs in pod.get("status", {}).get("containerStatuses", []) or []:
                    total += int(cs.get("restartCount", 0))
        if limit == 0:
            return total > 0
        return total >= limit

    # ------------------------------------------------------------ podgroups
    def _gang_coscheduling(self) -> bool:
        """True when the configured gang scheduler is the kube-scheduler
        coscheduling plugin (scheduler-plugins) rather than volcano."""
        return (self.config.gang_scheduler_name or "").lower() in (
            COSCHEDULING_SCHEDULER_NAMES
        )

    def _sync_pod_group(self, job: Job) -> None:
        """Gang PodGroup sync: minMember from schedulingPolicy.minAvailable
        or total replicas (reference: PodGroup lifecycle in kubeflow/common
        ReconcileJobs; CRD knobs manifests/base/kubeflow.org_tfjobs.yaml).
        The group object is rendered for whichever backend
        --gang-scheduler-name selects: volcano
        (scheduling.volcano.sh/v1beta1: queue/priorityClassName/minResources)
        or scheduler-plugins coscheduling (scheduling.x-k8s.io/v1alpha1:
        minResources/scheduleTimeoutSeconds; queue and priorityClass are
        volcano concepts with no coscheduling counterpart)."""
        total = sum(s.replicas or 0 for s in (job.replica_specs or {}).values())
        sp = job.run_policy.scheduling_policy
        min_member = total
        if sp is not None and sp.min_available is not None:
            min_member = sp.min_available
        coscheduling = self._gang_coscheduling()
        pg_kind = "CoschedulingPodGroup" if coscheduling else "PodGroup"
        spec: Dict[str, Any] = {"minMember": min_member}
        if sp is not None:
            if sp.min_resources:
                spec["minResources"] = sp.min_resources
            if coscheduling:
                if sp.schedule_timeout_seconds is not None:
                    spec["scheduleTimeoutSeconds"] = sp.schedule_timeout_seconds
            else:
                if sp.queue:
                    spec["queue"] = sp.queue
                if sp.priority_class:
                    spec["priorityClassName"] = sp.priority_class
        # knobs the selected backend cannot express — warned symmetrically
        # so no knob is ever dropped silently.  The warned values are
        # latched in a PodGroup annotation (not gated on the rendered-spec
        # diff: a foreign knob added to an already-synced job leaves the
        # rendered spec identical), so the event fires once per change and
        # survives controller restarts.
        ignored = {}
        if sp is not None:
            if coscheduling:
                if sp.queue:
                    ignored["queue"] = sp.queue
                if sp.priority_class:
                    ignored["priorityClass"] = sp.priority_class
            elif sp.schedule_timeout_seconds is not None:
                ignored["scheduleTimeoutSeconds"] = sp.schedule_timeout_seconds
        note = ",".join(f"{k}={v}" for k, v in sorted(ignored.items()))
        pg = {
            "apiVersion": ("scheduling.x-k8s.io/v1alpha1" if coscheduling
                           else "scheduling.volcano.sh/v1beta1"),
            "kind": "PodGroup",
            "metadata": {
                "name": job.name,
                "namespace": job.namespace,
                "ownerReferences": [
                    objects.owner_reference(
                        {"apiVersion": job.api_version, "kind": job.kind,
                         "metadata": job.metadata}
                    )
                ],
            },
            "spec": spec,
        }
        if note:
            pg["metadata"]["annotations"] = {IGNORED_KNOBS_ANNOTATION: note}
        try:
            existing = self.cluster.get(pg_kind, job.namespace, job.name)
            prev_note = (existing.get("metadata", {}).get("annotations", {})
                         .get(IGNORED_KNOBS_ANNOTATION, ""))
            if existing.get("spec") != pg["spec"] or prev_note != note:
                existing["spec"] = pg["spec"]
                ann = existing.setdefault("metadata", {}).setdefault(
                    "annotations", {})
                if note:
                    ann[IGNORED_KNOBS_ANNOTATION] = note
                else:
                    ann.pop(IGNORED_KNOBS_ANNOTATION, None)
                self.cluster.update(pg_kind, existing)
        except NotFoundError:
            prev_note = ""
            self.cluster.create(pg_kind, pg)
        if note and note != prev_note:
            backend = ("the scheduler-plugins coscheduling backend"
                       if coscheduling else "the volcano backend")
            self.cluster.record_event(
                job.to_dict(), "Warning", "GangSchedulingPolicy",
                f"schedulingPolicy {{{note}}} cannot be expressed by "
                f"{backend} and is ignored",
            )

    def _delete_pod_group(self, job: Job) -> None:
        # both backends' groups are tried: a --gang-scheduler-name flip
        # mid-job must not orphan the previous backend's PodGroup
        for pg_kind in ("PodGroup", "CoschedulingPodGroup"):
            try:
                self.cluster.delete(pg_kind, job.namespace, job.name)
            except Exception:
                pass

    # ------------------------------------------------------------ status io
    def _write_status(self, job: Job, old_status: common.JobStatus) -> None:
        """Status().Update only on diff (reference tfjob_controller.go:510-537).

        No GET-before-update: the sync already holds the job it read at
        dispatch time, so the write body is built from the in-hand object
        (name/namespace/uid + its resourceVersion) and sent through the
        status-subresource verb — one round trip instead of three
        (GET + spec PUT + status PUT on the REST backend).  Only status is
        ever written: the reference defaults the spec in-memory only, and
        the /status verb cannot touch spec by construction.  A conflict
        (the CR changed under the sync) falls back to exactly the read the
        fast path skipped — GET fresh, retry once; a second conflict
        propagates and requeues the sync like any transient error.  A
        successful write advances the stale-read fence so later syncs can
        tell a lagging read from fresh state."""
        new_status = job.status.to_dict()
        if new_status == old_status.to_dict():
            return
        meta = job.metadata or {}
        body = {
            "apiVersion": job.api_version,
            "kind": job.kind,
            "metadata": {
                "name": job.name,
                "namespace": job.namespace,
                "uid": job.uid,
                "resourceVersion": meta.get("resourceVersion"),
            },
            "status": new_status,
        }
        # sharded mode: the owning slot's fencing token rides in the write
        # body's annotations (never persisted — /status merges .status
        # only) so the store can reject a zombie's post-failover writes
        fence_token = self.fence(job.uid) if self.fence is not None else None
        if fence_token:
            from tf_operator_tpu.engine.sharding import FENCE_ANNOTATION

            body["metadata"]["annotations"] = {FENCE_ANNOTATION: fence_token}
        # legacy cluster doubles without the status verb keep the old
        # read-modify-write shape (fetch, overlay status, full update)
        update_status = getattr(self.cluster, "update_status", None)
        try:
            if update_status is not None:
                written = update_status(self.adapter.KIND, body)
            else:
                written = self._write_status_read_modify_write(job, new_status)
        except NotFoundError:
            return  # job deleted mid-sync; nothing to write status to
        except ConflictError:
            written = self._write_status_read_modify_write(
                job, new_status, update_status
            )
            if written is None:
                return
        rv = (written or {}).get("metadata", {}).get("resourceVersion")
        if self._rv_int(rv) is not None:
            self._rv_seen[job.key] = rv
        # crash-loop backoff observations happen HERE, per durably persisted
        # restart-counter increment, so _count tracks real restarts exactly
        # even when a failed delete/write makes the sync replay (old_status
        # is the fresh read, i.e. the previously persisted state)
        for rtype, rs in job.status.replica_statuses.items():
            prev = old_status.replica_statuses.get(rtype)
            prev_n = prev.restarts if prev else 0
            for n in range(prev_n + 1, rs.restarts + 1):
                delay = self._restart_backoff_delay(job, rtype, n)
                metrics.RESTART_BACKOFF.observe(
                    delay, {"kind": self.adapter.KIND},
                )
                if self.recorder is not None:
                    # per DURABLE increment, like the histogram: a replayed
                    # sync whose write failed never records a phantom
                    self.recorder.record(
                        job.key, "controller", "restart",
                        {"replica_type": rtype, "n": n,
                         "backoff": round(delay, 3)},
                        uid=job.uid,
                    )
        if self.recorder is not None:
            self._record_condition_transitions(job, old_status)

    def _record_condition_transitions(
        self, job: Job, old_status: common.JobStatus
    ) -> None:
        """Timeline records for conditions that just became True — only
        after the status write SUCCEEDED, so the timeline's Running /
        Restarting / terminal milestones (and the SLO histograms derived
        from them) reflect durably persisted state."""
        old = {c.type: c.status for c in old_status.conditions}
        for c in job.status.conditions:
            if c.status == "True" and old.get(c.type) != "True":
                self.recorder.record(
                    job.key, "controller", "condition",
                    {"type": c.type, "reason": c.reason}, uid=job.uid,
                )
        # full-strength transition: every desired replica active after a
        # persisted state in which some were not.  A partially-degraded
        # job (one of N workers dead) can keep its Running condition
        # through a whole restart incident, so this — not a condition
        # flip — is the durable repair-complete signal the MTTR clock
        # closes on (and at startup it marks "all replicas active").
        desired = sum(
            spec.replicas or 0
            for spec in (job.replica_specs or {}).values()
        )

        def _active(st: common.JobStatus) -> int:
            return sum(rs.active for rs in st.replica_statuses.values())

        if desired > 0 and _active(job.status) == desired > _active(old_status):
            self.recorder.record(
                job.key, "controller", "replicas_active",
                {"active": desired}, uid=job.uid,
            )

    def _write_status_read_modify_write(
        self, job: Job, new_status: Dict[str, Any], update_status=None
    ) -> Optional[Dict[str, Any]]:
        """The conflict-retry (and legacy-double) path: fetch the current
        object — the one read the fast path saved — overlay the computed
        status, write through whichever verb the cluster offers.  Returns
        None when the job is gone or unreadable (matching the historical
        swallow of GET failures); write errors propagate so the sync-level
        handling requeues."""
        try:
            current = self.cluster.get(self.adapter.KIND, job.namespace, job.name)
        except Exception:
            return None
        current["status"] = new_status
        if update_status is not None:
            # the retry must carry the fencing token too (only on the
            # status verb, whose merge discards body annotations; the
            # legacy full-update path below would PERSIST them)
            fence_token = self.fence(job.uid) if self.fence is not None else None
            if fence_token:
                from tf_operator_tpu.engine.sharding import FENCE_ANNOTATION

                current.setdefault("metadata", {}).setdefault(
                    "annotations", {}
                )[FENCE_ANNOTATION] = fence_token
            return update_status(self.adapter.KIND, current)
        return self.cluster.update(self.adapter.KIND, current)
