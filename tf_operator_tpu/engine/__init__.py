from tf_operator_tpu.engine.controller import EngineConfig, JobEngine, ReconcileResult
from tf_operator_tpu.engine.expectations import ControllerExpectations

__all__ = ["EngineConfig", "JobEngine", "ReconcileResult", "ControllerExpectations"]
