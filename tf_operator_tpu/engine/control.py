"""Pod/Service control — create/delete with controller ownerReferences.

Equivalent of kubeflow/common pkg/controller.v1/control
(RealPodControl/RealServiceControl, reference tfjob_controller.go:94-95) and
its FakePodControl test double (reference §4.2 tests count create/delete
calls instead of hitting an apiserver).
"""
from __future__ import annotations

import copy
import time
from typing import Any, Dict, List, Optional

from tf_operator_tpu.engine import metrics
from tf_operator_tpu.k8s import objects


class _OpTimer:
    """Times one control op into
    tpu_operator_control_op_duration_seconds{kind,verb} — the per-operation
    round-trip cost the transport pool and control fan-out exist to hide.
    Failed ops are observed too: a 429 that burned its retry budget is
    latency the sync paid."""

    def __init__(self, kind: str, verb: str) -> None:
        self._labels = {"kind": kind, "verb": verb}

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *_exc) -> None:
        metrics.CONTROL_OP_DURATION.observe(
            time.perf_counter() - self._t0, self._labels
        )


class PodControl:
    """Creates/deletes pods against a ClusterClient (FakeCluster or real)."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster

    def create_pod_with_controller_ref(
        self,
        namespace: str,
        pod_template: Dict[str, Any],
        owner: Dict[str, Any],
        controller_ref: Dict[str, Any],
    ) -> Dict[str, Any]:
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_template.get("metadata", {}).get("name", ""),
                "namespace": namespace,
                "labels": dict(pod_template.get("metadata", {}).get("labels", {}) or {}),
                "annotations": dict(
                    pod_template.get("metadata", {}).get("annotations", {}) or {}
                ),
                "ownerReferences": [copy.deepcopy(controller_ref)],
            },
            "spec": copy.deepcopy(pod_template.get("spec", {})),
            "status": {"phase": objects.POD_PENDING},
        }
        with _OpTimer("Pod", "create"):
            created = self.cluster.create_pod(pod)
        metrics.CONTROL_OPS.inc({"kind": "Pod", "verb": "create"})
        return created

    def delete_pod(self, namespace: str, name: str, owner: Dict[str, Any]) -> None:
        with _OpTimer("Pod", "delete"):
            self.cluster.delete_pod(namespace, name)
        metrics.CONTROL_OPS.inc({"kind": "Pod", "verb": "delete"})


class ServiceControl:
    def __init__(self, cluster) -> None:
        self.cluster = cluster

    def create_service_with_controller_ref(
        self,
        namespace: str,
        service: Dict[str, Any],
        owner: Dict[str, Any],
        controller_ref: Dict[str, Any],
    ) -> Dict[str, Any]:
        service = copy.deepcopy(service)
        service.setdefault("metadata", {})["ownerReferences"] = [
            copy.deepcopy(controller_ref)
        ]
        service["metadata"].setdefault("namespace", namespace)
        with _OpTimer("Service", "create"):
            created = self.cluster.create_service(service)
        metrics.CONTROL_OPS.inc({"kind": "Service", "verb": "create"})
        return created

    def delete_service(self, namespace: str, name: str, owner: Dict[str, Any]) -> None:
        with _OpTimer("Service", "delete"):
            self.cluster.delete_service(namespace, name)
        metrics.CONTROL_OPS.inc({"kind": "Service", "verb": "delete"})


class FakePodControl(PodControl):
    """Counts create/delete calls; optionally injects errors
    (reference tests' FakePodControl)."""

    def __init__(self, cluster=None) -> None:
        super().__init__(cluster)
        self.templates: List[Dict[str, Any]] = []
        self.deleted: List[str] = []
        self.create_error: Optional[Exception] = None

    def create_pod_with_controller_ref(self, namespace, pod_template, owner, controller_ref):
        if self.create_error is not None:
            raise self.create_error
        self.templates.append(copy.deepcopy(pod_template))
        if self.cluster is not None:
            return super().create_pod_with_controller_ref(
                namespace, pod_template, owner, controller_ref
            )
        return pod_template

    def delete_pod(self, namespace, name, owner):
        self.deleted.append(f"{namespace}/{name}")
        if self.cluster is not None:
            super().delete_pod(namespace, name, owner)
