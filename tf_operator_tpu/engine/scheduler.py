"""Cluster scheduler — gang admission, topology-aware bin-packing,
priority preemption.

The reference delegates all placement to volcano PodGroups (PAPER.md §L1,
L0 row); this operator had none — pods landed wherever the fake kubelet
put them, with no node or slice inventory anywhere.  This module is that
missing layer, as a simulated-cluster scheduler the engine consults
before every pod create:

  - **Node inventory**: Node objects in the cluster store (kind "Node",
    cluster-scoped) model TPU slices.  A node IS one slice: its chip
    capacity comes from its ``kubeflow.org/slice-shape`` label (v5e-1 /
    v5e-8 / v5e-256 — the same shapes the warm pool pre-provisions) and
    its accelerator generation from ``kubeflow.org/tpu-generation``
    (heterogeneous clusters mix v5e and v5p slices).  Pod templates
    request chips through the same slice-shape annotation the warm pool
    reads, so the two subsystems always agree on what a replica needs.
  - **Gang admission**: a job's whole member set reserves node capacity
    ATOMICALLY — a PodGroup-style reservation held in one scheduler —
    or not at all.  The reservation is the unit of atomicity: capacity
    for every member is taken under one lock before any pod exists, so
    a chaos storm failing pod creates mid-gang leaves a whole
    reservation (the next sync finishes creating into it), never a
    partial one.  A job that cannot be admitted is *pending*: the
    engine stamps a ``Scheduling`` condition + event so
    ``tpu-jobs describe`` says why the job has no pods.
  - **Bin-packing policies** (pluggable, ``--scheduler-policy``):
    ``spread`` places each member on the emptiest fitting node (the
    kube-scheduler LeastAllocated baseline — fragments the cluster),
    ``packed`` best-fits (Tesserae-style placement scoring, arXiv
    2508.04953 — keeps big contiguous blocks free), and
    ``throughput_ratio`` (Gavel, arXiv 2008.09213) prefers the node
    generation where the job's normalized throughput is highest, so
    fast slices go to the jobs that speed up most; ties break packed.
  - **Priority preemption**: a gang that does not fit may evict
    lower-priority gangs (``kubeflow.org/priority`` annotation, or a
    named priorityClass) when — and only when — the plan provably frees
    enough capacity.  Eviction is graceful SIGTERM: members die with
    exit code 143, which PR 3's ExitCode machinery already counts as a
    retryable restart, the victim's reservation is released wholesale,
    and its next sync re-enters gang admission — preempted gangs
    requeue, they never orphan.  If any eviction write fails (chaos
    storm), the preemption ABORTS with the victim's reservation intact:
    already-killed members restart into their still-held slots.

One scheduler per operator process, like the warm pool: ShardedOperator
shares it across shards (admission is lock-serialized; reservations are
keyed by job UID so shard failover changes nothing), and engines without
one (`scheduler=None`, the default) bypass every seam — the pre-scheduler
chaos goldens stay byte-identical.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.engine import metrics, warmpool
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.fake import ApiError, ConflictError, NotFoundError

# Node inventory labels: a Node IS one TPU slice — its shape names its
# chip capacity (same vocabulary as the warm pool's standby shapes) and
# its generation feeds the heterogeneity-aware policy.
SLICE_SHAPE_LABEL = "kubeflow.org/slice-shape"
GENERATION_LABEL = "kubeflow.org/tpu-generation"
TPU_RESOURCE = "google.com/tpu"
DEFAULT_GENERATION = "v5e"

# Job-side knobs, read off the job CR's metadata:
#   priority: integer; higher preempts lower.  schedulingPolicy.
#     priorityClass names map through PRIORITY_CLASSES as a fallback.
#   throughput-ratios: "v5e=1.0,v5p=2.4" — the job's relative speed per
#     accelerator generation (Gavel's throughput matrix, one row).
PRIORITY_ANNOTATION = "kubeflow.org/priority"
THROUGHPUT_ANNOTATION = "kubeflow.org/throughput-ratios"
PRIORITY_CLASSES = {"system": 1000, "high": 100, "default": 0, "low": -100}
# Elastic opt-in: a job carrying this annotation (an integer floor) may be
# SHRUNK to that many replicas per type — through the controller's full
# drain -> checkpoint -> resume path — when a higher-priority gang needs
# its chips, instead of being evicted outright ("preemption = resize to
# what fits").  Absent = rigid: the gang is all-or-nothing, as before.
MIN_REPLICAS_ANNOTATION = "kubeflow.org/min-replicas"

# Stamped into every scheduled pod's annotations at create time: the
# member's reserved node.  resync() rebuilds reservations from it after
# an operator restart (spec.nodeName is the fallback for warm-claimed
# pods, whose immutable spec kept the standby's node).
ASSIGNED_NODE_ANNOTATION = "kubeflow.org/assigned-node"

REASON_PREEMPTED = "GangPreempted"
REASON_SHRUNK = "GangShrunk"


def chips_of_shape(shape: str) -> int:
    """Chip count of a slice shape: the numeric tail of "v5e-8" etc.
    Unparsable shapes count as one chip — a malformed annotation must
    not make a job unschedulable forever."""
    tail = (shape or "").rsplit("-", 1)[-1]
    try:
        return max(1, int(tail))
    except ValueError:
        return 1


def parse_node_spec(spec: str) -> Tuple[str, str, str]:
    """--node NAME=SHAPE[:GEN] -> (name, shape, generation)."""
    name, sep, rest = spec.partition("=")
    if not sep or not name or not rest:
        raise ValueError(f"--node wants NAME=SHAPE[:GEN], got {spec!r}")
    shape, _, gen = rest.partition(":")
    return name, shape, gen or DEFAULT_GENERATION


def make_node(name: str, shape: str, generation: str = DEFAULT_GENERATION
              ) -> Dict[str, Any]:
    chips = chips_of_shape(shape)
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                SLICE_SHAPE_LABEL: shape,
                GENERATION_LABEL: generation,
            },
        },
        "status": {
            "capacity": {TPU_RESOURCE: str(chips)},
            "allocatable": {TPU_RESOURCE: str(chips)},
        },
    }


def ensure_nodes(cluster, specs: List[str]) -> None:
    """Create the --node inventory (idempotent: an already-present node
    is left exactly as it is, so restarts never reset a topology)."""
    for spec in specs:
        name, shape, gen = parse_node_spec(spec)
        try:
            cluster.create("Node", make_node(name, shape, gen))
        except ConflictError:
            pass


def node_chips(node: Dict[str, Any]) -> int:
    """A node's chip capacity: status.capacity wins, slice-shape label is
    the fallback (hand-made fixtures may carry only one)."""
    cap = ((node.get("status") or {}).get("capacity") or {}).get(TPU_RESOURCE)
    if cap is not None:
        try:
            return max(0, int(cap))
        except (TypeError, ValueError):
            pass
    return chips_of_shape(objects.labels_of(node).get(SLICE_SHAPE_LABEL, ""))


def priority_of_cr(cr: Dict[str, Any]) -> int:
    """priority_of over a raw CR dict (resync reads stored objects, not
    api.Job instances): annotation first, then a named/int priorityClass
    under spec.runPolicy.schedulingPolicy (or legacy spec.schedulingPolicy)."""
    ann = (cr.get("metadata") or {}).get("annotations") or {}
    raw = ann.get(PRIORITY_ANNOTATION)
    if raw is not None:
        try:
            return int(raw)
        except (TypeError, ValueError):
            pass
    spec = cr.get("spec") or {}
    sp = (
        (spec.get("runPolicy") or {}).get("schedulingPolicy")
        or spec.get("schedulingPolicy") or {}
    )
    pc = sp.get("priorityClass")
    if pc:
        if pc in PRIORITY_CLASSES:
            return PRIORITY_CLASSES[pc]
        try:
            return int(pc)
        except ValueError:
            pass
    return 0


def priority_of(job) -> int:
    """Job priority: the integer annotation wins; a named priorityClass
    (schedulingPolicy.priorityClass) maps through PRIORITY_CLASSES or
    parses as an int; everything else is 0."""
    ann = (getattr(job, "metadata", None) or {}).get("annotations") or {}
    raw = ann.get(PRIORITY_ANNOTATION)
    if raw is not None:
        try:
            return int(raw)
        except (TypeError, ValueError):
            pass
    sp = getattr(getattr(job, "run_policy", None), "scheduling_policy", None)
    pc = getattr(sp, "priority_class", None)
    if pc:
        if pc in PRIORITY_CLASSES:
            return PRIORITY_CLASSES[pc]
        try:
            return int(pc)
        except ValueError:
            pass
    return 0


def _parse_min_replicas(raw) -> Optional[int]:
    if raw is None:
        return None
    try:
        return max(0, int(raw))
    except (TypeError, ValueError):
        return None


def min_replicas_of(job) -> Optional[int]:
    """The job's elastic floor (MIN_REPLICAS_ANNOTATION), or None when the
    job is rigid (no shrink-before-evict eligibility)."""
    ann = (getattr(job, "metadata", None) or {}).get("annotations") or {}
    return _parse_min_replicas(ann.get(MIN_REPLICAS_ANNOTATION))


def min_replicas_of_cr(cr: Dict[str, Any]) -> Optional[int]:
    """min_replicas_of over a raw CR dict (resync reads stored objects)."""
    ann = (cr.get("metadata") or {}).get("annotations") or {}
    return _parse_min_replicas(ann.get(MIN_REPLICAS_ANNOTATION))


def throughput_ratios_of(job) -> Dict[str, float]:
    """Per-generation relative throughput ("v5e=1.0,v5p=2.4"); absent or
    malformed entries default to 1.0-everywhere (generation-indifferent)."""
    ann = (getattr(job, "metadata", None) or {}).get("annotations") or {}
    raw = ann.get(THROUGHPUT_ANNOTATION)
    out: Dict[str, float] = {}
    for part in (raw or "").split(","):
        gen, sep, val = part.strip().partition("=")
        if not sep:
            continue
        try:
            out[gen] = float(val)
        except ValueError:
            continue
    return out


# ------------------------------------------------------------------ policies
# A policy scores one candidate node for one member; the member goes to
# the highest score.  Candidates are iterated in name order, so ties
# resolve to the lexicographically first node — deterministic per state.
def _score_spread(ctx: "GangContext", gen: str, free_after: int) -> Tuple:
    return (free_after,)


def _score_packed(ctx: "GangContext", gen: str, free_after: int) -> Tuple:
    return (-free_after,)


def _score_throughput_ratio(ctx: "GangContext", gen: str, free_after: int
                            ) -> Tuple:
    ratios = ctx.throughput or {}
    best = max(ratios.values()) if ratios else 1.0
    ratio = ratios.get(gen, 1.0) / best if best > 0 else 1.0
    return (ratio, -free_after)


POLICIES: Dict[str, Callable[["GangContext", str, int], Tuple]] = {
    "spread": _score_spread,
    "packed": _score_packed,
    "throughput_ratio": _score_throughput_ratio,
}


@dataclass
class GangContext:
    """Per-gang data a policy may consult."""

    job_key: str
    priority: int = 0
    throughput: Optional[Dict[str, float]] = None


@dataclass
class Reservation:
    """One admitted gang: every member's chips and reserved node.  The
    invariant the whole subsystem exists for: assignments covers EVERY
    member or the reservation does not exist — there is no partial
    state, under any interleaving."""

    job_uid: str
    job_key: str
    kind: str
    namespace: str
    priority: int
    members: Dict[str, int]            # member name -> chips
    assignments: Dict[str, str]        # member name -> node name
    admitted_at: float = 0.0
    throughput: Dict[str, float] = field(default_factory=dict)
    # member name -> ACTUAL pod name, for members whose pod is not named
    # after them (warm claims keep the standby's name) — eviction and
    # drain must kill the pod that exists, not the name the gang uses
    pod_names: Dict[str, str] = field(default_factory=dict)
    # elastic floor (MIN_REPLICAS_ANNOTATION): when set, the preemption
    # planner may shrink this gang to `min_replicas` per replica type
    # instead of evicting it; None = rigid
    min_replicas: Optional[int] = None

    def pod_of(self, member: str) -> str:
        return self.pod_names.get(member, member)


class ClusterScheduler:
    """Gang admission + bin-packing + preemption over the Node inventory.

    One per process; every method is safe under the instance lock.  The
    node inventory is cached (nodes are near-static) and kept fresh by a
    store subscription, so admission never LISTs the apiserver on the
    sync hot path — and never trips over a chaos storm on reads."""

    def __init__(
        self,
        cluster,
        policy: str = "packed",
        clock=time.time,
        retry_interval: float = 5.0,
        enable_preemption: bool = True,
        note: Optional[Callable[[str], None]] = None,
        shrink_before_evict: bool = False,
    ) -> None:
        if policy not in POLICIES:
            raise ValueError(
                f"unknown scheduler policy {policy!r} "
                f"(choose from {sorted(POLICIES)})"
            )
        self.cluster = cluster
        self.policy_name = policy
        self._score = POLICIES[policy]
        self.clock = clock
        self.retry_interval = retry_interval
        self.enable_preemption = enable_preemption
        # shrink-before-evict (requires the controller's --elastic-resize
        # to actually execute the shrink): eligible elastic victims are
        # resized down to their floor before anyone is fully evicted.
        # Off (default) keeps the evict-only planner byte-identical.
        self.shrink_before_evict = shrink_before_evict
        # deterministic-log hook (FaultInjector.note in soaks): admission,
        # preemption, and drain decisions land in the seeded event log
        self.note = note or (lambda line: None)
        # job flight recorder (engine/timeline.py): when wired by the
        # manager, every bind / preemption / drain eviction also lands in
        # the affected jobs' timelines (victim AND beneficiary), so
        # "why is job X pending" is answerable per job, not just from
        # the cluster-wide log.  None disables the seam.
        self.recorder = None
        self._lock = threading.RLock()
        # node name -> (capacity chips, generation)
        self._nodes: Dict[str, Tuple[int, str]] = {}
        # cordoned node names: placement never offers them (existing
        # reservations stay — cordon is "no NEW work", not eviction).
        # Mirrors spec.unschedulable on the Node object, so the state
        # survives resync and is visible to other actors (the chaos
        # kubelet's warm-standby placement consults it)
        self._cordoned: set = set()
        self._reservations: Dict[str, Reservation] = {}
        # pending gangs: job_uid -> (first time admission failed,
        # job_key, kind) — feeds the bind-latency histogram and the
        # pending gauge; key+kind let a deleted job's entry be swept by
        # release_key() without hitting a same-named job of another kind
        self._pending_since: Dict[str, Tuple[float, str, str]] = {}
        # per-job-key members evicted by preemption/drain — the restart
        # accounting cross-check the soaks assert against (each evicted
        # member is exactly one ExitCode restart)
        self.evictions: Dict[str, int] = {}
        cluster.subscribe("Node", self._on_node_event)

    # --------------------------------------------------------------- inventory
    def _on_node_event(self, event_type: str, node: Dict[str, Any]) -> None:
        name = objects.name_of(node)
        with self._lock:
            if event_type == "DELETED":
                self._nodes.pop(name, None)
                self._cordoned.discard(name)
            else:
                self._nodes[name] = (
                    node_chips(node),
                    objects.labels_of(node).get(
                        GENERATION_LABEL, DEFAULT_GENERATION
                    ),
                )
                if (node.get("spec") or {}).get("unschedulable"):
                    self._cordoned.add(name)
                else:
                    self._cordoned.discard(name)
            self._update_gauges_locked()

    def resync(self) -> None:
        """Load the Node inventory and rebuild reservations from live pods
        (operator restart: like the warm pool, scheduler state is derived
        state — the cluster is the source of truth).  A pod's reserved
        node is its assigned-node annotation, falling back to
        spec.nodeName (warm-claimed pods keep the standby's immutable
        spec).  Rebuilt reservations may be partial mid-restart; the
        owning job's first sync re-admits and completes them."""
        try:
            nodes = self.cluster.list("Node")
        except (ApiError, OSError):
            nodes = []
        with self._lock:
            for node in nodes:
                name = objects.name_of(node)
                self._nodes[name] = (
                    node_chips(node),
                    objects.labels_of(node).get(
                        GENERATION_LABEL, DEFAULT_GENERATION
                    ),
                )
                # cordon state is derived state too: a restarted
                # scheduler must not re-place onto a node someone
                # cordoned before the crash
                if (node.get("spec") or {}).get("unschedulable"):
                    self._cordoned.add(name)
                else:
                    self._cordoned.discard(name)
        try:
            pods = self.cluster.list_pods()
        except (ApiError, OSError):
            pods = []
        # one owner-CR read per job, for its PRIORITY (rebuilding with a
        # default 0 would let any positive-priority arrival preempt a
        # high-priority gang in the window before its first post-restart
        # sync re-asserts itself — priority inversion at the worst time)
        # and its elastic floor (a restarted operator must not forget a
        # victim's shrink eligibility mid-capacity-crunch)
        owner_info: Dict[Tuple[str, str, str], Tuple[int, Optional[int]]] = {}

        def info_for(ref: Dict[str, Any], namespace: str
                     ) -> Tuple[int, Optional[int]]:
            key = (ref.get("kind", ""), namespace, ref.get("name", ""))
            if key not in owner_info:
                try:
                    cr = self.cluster.get(*key)
                    owner_info[key] = (
                        priority_of_cr(cr), min_replicas_of_cr(cr)
                    )
                except (ApiError, OSError):
                    owner_info[key] = (0, None)
            return owner_info[key]

        for pod in pods:
            ref = objects.get_controller_of(pod)
            if ref is None or objects.pod_phase(pod) in (
                objects.POD_SUCCEEDED, objects.POD_FAILED
            ):
                continue
            ann = (pod.get("metadata") or {}).get("annotations") or {}
            node = ann.get(ASSIGNED_NODE_ANNOTATION) or (
                pod.get("spec") or {}
            ).get("nodeName")
            if not node:
                continue
            shape = ann.get(SLICE_SHAPE_LABEL) or objects.labels_of(pod).get(
                SLICE_SHAPE_LABEL, ""
            )
            # a warm-claimed pod keeps its standby NAME; its member
            # identity (the name the gang knows it by) rides the
            # late-binding annotation — rebuilding under the pod name
            # would leave the spec's member unadopted and double-book
            member = (
                ann.get(warmpool.WARM_BOUND_NAME_ANNOTATION)
                or objects.name_of(pod)
            )
            with self._lock:
                res = self._reservations.get(ref.get("uid", ""))
                if res is None:
                    prio, floor = info_for(ref, objects.namespace_of(pod))
                    res = Reservation(
                        job_uid=ref.get("uid", ""),
                        job_key=(
                            f"{objects.namespace_of(pod)}/{ref.get('name', '')}"
                        ),
                        kind=ref.get("kind", ""),
                        namespace=objects.namespace_of(pod),
                        priority=prio,
                        members={},
                        assignments={},
                        admitted_at=self.clock(),
                        min_replicas=floor,
                    )
                    self._reservations[res.job_uid] = res
                res.members[member] = chips_of_shape(shape)
                res.assignments[member] = node
                if member != objects.name_of(pod):
                    res.pod_names[member] = objects.name_of(pod)
        with self._lock:
            self._update_gauges_locked()

    def _free_locked(self) -> Dict[str, int]:
        free = {name: cap for name, (cap, _gen) in self._nodes.items()}
        for res in self._reservations.values():
            for member, node in res.assignments.items():
                if node in free:
                    free[node] -= res.members.get(member, 0)
        return free

    def free_chips(self) -> Dict[str, int]:
        with self._lock:
            return self._free_locked()

    # ----------------------------------------------------------------- cordon
    def cordoned_nodes(self) -> frozenset:
        with self._lock:
            return frozenset(self._cordoned)

    def _write_unschedulable(self, node: str, value: bool) -> None:
        """Mirror cordon state onto the Node object's spec.unschedulable
        (best-effort: the in-memory set is authoritative for THIS
        scheduler; the write makes the state survive resync and shows
        it to other actors — kubectl semantics)."""
        try:
            for obj in self.cluster.list("Node"):
                if objects.name_of(obj) != node:
                    continue
                spec = obj.setdefault("spec", {})
                if bool(spec.get("unschedulable")) == value:
                    return
                spec["unschedulable"] = value
                self.cluster.update("Node", obj)
                return
        except (ApiError, OSError):
            return

    def cordon(self, node: str) -> None:
        """Mark `node` unschedulable: existing reservations stay (cordon
        is not eviction), but placement never offers it until
        uncordon().  Idempotent."""
        with self._lock:
            if node in self._cordoned:
                return
            self._cordoned.add(node)
        self._write_unschedulable(node, True)
        self.note(f"cordon node={node}")

    def uncordon(self, node: str) -> None:
        """Restore `node` to the schedulable pool.  Idempotent."""
        with self._lock:
            if node not in self._cordoned:
                return
            self._cordoned.discard(node)
        self._write_unschedulable(node, False)
        self.note(f"uncordon node={node}")

    def reserved_members(self, job_uid: str) -> int:
        with self._lock:
            res = self._reservations.get(job_uid)
            return len(res.assignments) if res else 0

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending_since)

    # ----------------------------------------------------------------- gauges
    def _update_gauges_locked(self) -> None:
        metrics.SCHEDULER_PENDING_GANGS.set(len(self._pending_since))
        free = self._free_locked()
        total_free = sum(max(0, f) for f in free.values())
        largest = max((max(0, f) for f in free.values()), default=0)
        # 0 = one contiguous block holds all free chips (a big gang can
        # land); -> 1 = free capacity is crumbs no large slice fits in
        frag = 1.0 - (largest / total_free) if total_free > 0 else 0.0
        metrics.SCHEDULER_FRAGMENTATION.set(frag)

    # -------------------------------------------------------------- placement
    def _place_locked(
        self,
        members: Dict[str, int],
        free: Dict[str, int],
        ctx: GangContext,
    ) -> Optional[Dict[str, str]]:
        """Assign every member a node within `free`, policy-scored, or
        None when any member cannot fit.  First-fit-decreasing: big
        members place first so crumbs are spent on small ones.  Mutates
        `free` only on full success (all-or-nothing by construction: the
        tentative dict is local until every member lands)."""
        assignment: Dict[str, str] = {}
        tentative = dict(free)
        for member in sorted(members, key=lambda m: (-members[m], m)):
            chips = members[member]
            best_node, best_score = None, None
            for node in sorted(tentative):
                if node in self._cordoned:
                    # a draining/cordoned node takes no NEW placements
                    # — evicted gangs and replenishment must not land
                    # back on the node mid-drain
                    continue
                cap_free = tentative[node]
                if cap_free < chips:
                    continue
                gen = self._nodes[node][1]
                score = self._score(ctx, gen, cap_free - chips)
                if best_score is None or score > best_score:
                    best_node, best_score = node, score
            if best_node is None:
                return None
            assignment[member] = best_node
            tentative[best_node] -= chips
        free.clear()
        free.update(tentative)
        return assignment

    # -------------------------------------------------------------- admission
    def admit(
        self,
        job_key: str,
        job_uid: str,
        kind: str,
        namespace: str,
        members: Dict[str, int],
        priority: int = 0,
        existing: Optional[Dict[str, str]] = None,
        throughput: Optional[Dict[str, float]] = None,
        pod_names: Optional[Dict[str, str]] = None,
        min_replicas: Optional[int] = None,
    ) -> Tuple[bool, str]:
        """Admit (or re-assert) the gang atomically.  Returns
        (admitted, message).  Idempotent: an unchanged admitted gang is a
        no-op.  A changed member set OR changed chip demand (scale,
        slice-shape edit) keeps live-pod-anchored members in place and
        atomically re-places the rest under the new demand — the resize
        either fully lands or the reservation stays at its previous full
        shape and (False, why) is returned.  An EMPTY member set is a
        resize to zero: the reservation is released.

        `existing` maps members to the nodes their live pods already sit
        on (informer snapshot): admission adopts those placements as-is —
        physical reality outranks the model — so a restarted operator
        reconverges without moving a single pod.  `pod_names` maps
        members whose pod is not named after them (warm claims) to the
        actual pod name, so eviction/drain kill the pod that exists."""
        if not members:
            # resize to zero holds no capacity (the elastic contract:
            # "preemption = resize to 0") — a leaked reservation here
            # would park every later gang against phantom demand
            self.release(job_uid)
            return True, ""
        ctx = GangContext(
            job_key=job_key, priority=priority, throughput=throughput
        )
        with self._lock:
            res = self._reservations.get(job_uid)
            if res is not None:
                res.priority = priority
                res.throughput = dict(throughput or {})
                res.min_replicas = min_replicas
                if pod_names:
                    res.pod_names.update(
                        {m: n for m, n in pod_names.items() if m in members}
                    )
                # full-dict comparison: identical member NAMES with a
                # changed chip demand (slice-shape edit) is a resize,
                # not a no-op — accepting it unchecked would over-commit
                # nodes where only the old demand is reserved
                if res.members == members:
                    # an admitted gang is by definition not pending: a
                    # failed-then-reverted resize must not leave a stale
                    # pending entry (gauge over-reports, and a later
                    # bind would measure latency from the dead attempt)
                    if job_uid in self._pending_since:
                        self._clear_pending_locked(
                            job_uid, count_bind=False
                        )
                        self._update_gauges_locked()
                    return True, ""
                # resize: drop members no longer in the spec, re-place
                # members whose demand changed (unless a live pod anchors
                # them — reality wins), extend with the new ones.  The
                # WHOLE resize is all-or-nothing: a failed placement
                # restores the snapshot, so the reservation is always the
                # old full shape or the new one — never a neither-shape
                # subset (a resize mixing removals and additions would
                # otherwise strand one)
                snap = (
                    dict(res.members), dict(res.assignments),
                    dict(res.pod_names),
                )
                for gone in [m for m in res.members if m not in members]:
                    res.members.pop(gone, None)
                    res.assignments.pop(gone, None)
                    res.pod_names.pop(gone, None)
                for m, chips in members.items():
                    if (
                        m in res.members
                        and res.members[m] != chips
                        and m not in (existing or {})
                    ):
                        res.assignments.pop(m, None)
                # price every still-assigned member at the NEW demand
                # before computing free, so the placement below sees the
                # resize's real footprint
                res.members = dict(members)
                missing = {
                    m: c for m, c in members.items()
                    if m not in res.assignments
                }
                adopted = self._adopt_locked(res, missing, existing)
                missing = {
                    m: c for m, c in missing.items() if m not in adopted
                }
                if missing:
                    free = self._free_locked()
                    placed = self._place_locked(missing, free, ctx)
                    if placed is None and self.enable_preemption:
                        # a high-priority gang scaling up may preempt
                        # exactly like a fresh arrival (the docs promise
                        # priority, not priority-only-on-first-admission)
                        placed = self._preempt_and_place_locked(
                            res, missing, ctx, registered=True
                        )
                    if placed is None:
                        (res.members, res.assignments,
                         res.pod_names) = snap
                        self._mark_pending_locked(job_uid, job_key, kind)
                        self._update_gauges_locked()
                        return False, self._shortfall_msg(missing)
                    res.assignments.update(placed)
                self._clear_pending_locked(job_uid, count_bind=False)
                self._update_gauges_locked()
                return True, ""

            # fresh admission
            res = Reservation(
                job_uid=job_uid, job_key=job_key, kind=kind,
                namespace=namespace, priority=priority,
                members=dict(members), assignments={},
                admitted_at=self.clock(),
                throughput=dict(throughput or {}),
                pod_names={
                    m: n for m, n in (pod_names or {}).items()
                    if m in members
                },
                min_replicas=min_replicas,
            )
            adopted = self._adopt_locked(res, members, existing)
            missing = {m: c for m, c in members.items() if m not in adopted}
            free = self._free_for_candidate_locked(res)
            placed = self._place_locked(missing, free, ctx) if missing else {}
            if placed is None and self.enable_preemption:
                placed = self._preempt_and_place_locked(res, missing, ctx)
            if placed is None:
                self._mark_pending_locked(job_uid, job_key, kind)
                self._update_gauges_locked()
                return False, self._shortfall_msg(missing)
            res.assignments.update(placed)
            self._reservations[job_uid] = res
            self._clear_pending_locked(job_uid, count_bind=True)
            self._update_gauges_locked()
            self.note(
                f"gang_admit job={job_key} members={len(members)} "
                f"policy={self.policy_name}"
            )
            self._record(
                job_key, "gang_admitted",
                {"members": len(members), "policy": self.policy_name,
                 "nodes": sorted(set(res.assignments.values()))},
                uid=job_uid,
            )
            return True, ""

    def _free_for_candidate_locked(self, res: Reservation) -> Dict[str, int]:
        """Free chips with the candidate's own (not-yet-registered)
        adopted members deducted — _free_locked only sees registered
        reservations, and forgetting the candidate's live pods would
        offer their chips to its own placement (or to a preemption plan)
        twice."""
        free = self._free_locked()
        for member, node in res.assignments.items():
            if node in free:
                free[node] -= res.members.get(member, 0)
        return free

    def _adopt_locked(
        self,
        res: Reservation,
        members: Dict[str, int],
        existing: Optional[Dict[str, str]],
    ) -> Dict[str, str]:
        """Record already-placed members (live pods) verbatim."""
        adopted = {}
        for member, node in (existing or {}).items():
            if member in members and node:
                res.assignments[member] = node
                adopted[member] = node
        return adopted

    def _shortfall_msg(self, missing: Dict[str, int]) -> str:
        need = sum(missing.values())
        with self._lock:
            free = self._free_locked()
        total_free = sum(max(0, f) for f in free.values())
        largest = max((max(0, f) for f in free.values()), default=0)
        return (
            f"waiting for capacity: {len(missing)} replica(s) need "
            f"{need} chip(s); cluster has {total_free} free "
            f"(largest contiguous slice {largest})"
        )

    def _mark_pending_locked(
        self, job_uid: str, job_key: str, kind: str = ""
    ) -> None:
        self._pending_since.setdefault(
            job_uid, (self.clock(), job_key, kind)
        )

    def _clear_pending_locked(self, job_uid: str, count_bind: bool) -> None:
        entry = self._pending_since.pop(job_uid, None)
        if count_bind:
            metrics.SCHEDULER_BINDS.inc({"policy": self.policy_name})
            metrics.SCHEDULER_BIND_LATENCY.observe(
                max(0.0, self.clock() - entry[0]) if entry is not None
                else 0.0,
                {"policy": self.policy_name},
            )

    # -------------------------------------------------------------- lifecycle
    def planned_node(self, job_uid: str, member: str) -> Optional[str]:
        with self._lock:
            res = self._reservations.get(job_uid)
            return res.assignments.get(member) if res else None

    def rebind(
        self, job_uid: str, member: str, actual_node: str,
        pod_name: Optional[str] = None,
    ) -> None:
        """A warm-pool claim landed the member on `actual_node` (the
        standby's immutable spec) instead of its planned slot: move the
        reservation to where the pod physically is, and remember the
        pod's ACTUAL name (the standby's) so eviction/drain can kill it.
        Reality wins even when it over-commits the node — the accounting
        must describe the cluster, not wish it were different."""
        with self._lock:
            res = self._reservations.get(job_uid)
            if res is None:
                return
            if pod_name and pod_name != member:
                res.pod_names[member] = pod_name
            if not actual_node or res.assignments.get(member) == actual_node:
                return
            res.assignments[member] = actual_node
            self._update_gauges_locked()

    def release(self, job_uid: str) -> None:
        with self._lock:
            res = self._reservations.pop(job_uid, None)
            pending = self._pending_since.pop(job_uid, None)
            if res is not None or pending is not None:
                # a pending-only release must refresh the gauge too, or
                # scheduler_pending_gangs reads stale after a waiting
                # gang is suspended/finished
                self._update_gauges_locked()

    def release_key(self, job_key: str, kind: Optional[str] = None) -> None:
        """Release by namespace/name key — the path for a DELETED job,
        where the engine no longer holds the UID.  Sweeps both the
        reservation (capacity comes back) and any pending entry (a gang
        that will never be admitted must not hold the pending gauge up).
        `kind` scopes the sweep: every kind's engine shares this one
        scheduler, and a TFJob named ns/x dying must not release a live
        PyTorchJob ns/x's reservation."""
        with self._lock:
            for uid, res in list(self._reservations.items()):
                if res.job_key == job_key and (
                    kind is None or res.kind == kind
                ):
                    self._reservations.pop(uid, None)
            for uid, (_since, key, pkind) in list(
                self._pending_since.items()
            ):
                if key == job_key and (kind is None or pkind == kind):
                    self._pending_since.pop(uid, None)
            self._update_gauges_locked()

    # ------------------------------------------------------------- preemption
    def _shrink_drop_locked(self, victim: Reservation) -> Dict[str, str]:
        """member -> node for the members a shrink-to-floor would drop:
        per replica type, every index at or above the victim's elastic
        floor (the spec patch sets replicas = min(current, floor), so
        indices 0..floor-1 survive).  Empty when the victim is rigid or
        already at its floor — i.e. not shrinkable."""
        floor = victim.min_replicas
        if floor is None:
            return {}
        groups: Dict[str, List[Tuple[int, str]]] = {}
        for member in victim.assignments:
            parts = member.rsplit("-", 2)
            if len(parts) != 3:
                continue
            try:
                idx = int(parts[2])
            except ValueError:
                continue
            groups.setdefault(parts[1], []).append((idx, member))
        drop: Dict[str, str] = {}
        for entries in groups.values():
            entries.sort()
            for _idx, member in entries[floor:]:
                drop[member] = victim.assignments[member]
        return drop

    def _request_shrink_locked(
        self, victim: Reservation, preemptor: Reservation
    ) -> bool:
        """Patch the victim job's SPEC down to its elastic floor
        (replicas = min(current, floor) per type) so the victim's own
        controller executes the shrink through the full elastic-resize
        path: drain with a final checkpoint, reshard, resume at the
        floor.  The reservation is NOT touched here — capacity frees
        when the victim's resize admits the smaller shape, and the
        preemptor stays pending until then.  Idempotent: a spec already
        at the floor is a quiet no-op (retry syncs re-plan without
        re-noting)."""
        floor = victim.min_replicas or 0
        name = victim.job_key.partition("/")[2]
        try:
            cr = self.cluster.get(victim.kind, victim.namespace, name)
        except (ApiError, OSError):
            return False
        spec = cr.get("spec") or {}
        rs_key = next(
            (k for k in spec if k.endswith("ReplicaSpecs")), None
        )
        if rs_key is None:
            return False
        changed = False
        for rspec in (spec.get(rs_key) or {}).values():
            cur = int(rspec.get("replicas") or 0)
            if cur > floor:
                rspec["replicas"] = floor
                changed = True
        if not changed:
            return True  # already at/below the floor: shrink in flight
        try:
            self.cluster.update(victim.kind, cr)
        except (ApiError, OSError):
            return False
        metrics.SCHEDULER_SHRINKS.inc({"policy": self.policy_name})
        try:
            self.cluster.record_event(
                {"kind": victim.kind,
                 "metadata": {"name": name,
                              "namespace": victim.namespace}},
                "Normal", REASON_SHRUNK,
                f"gang shrunk to min-replicas={floor} for higher-priority "
                f"{preemptor.job_key} (priority {preemptor.priority} > "
                f"{victim.priority}); degrading instead of evicting",
            )
        except Exception:  # noqa: BLE001 — eventing is best-effort
            pass
        self.note(
            f"shrink gang={victim.job_key} floor={floor} "
            f"by={preemptor.job_key}"
        )
        self._record(
            victim.job_key, "shrink_requested",
            {"by": preemptor.job_key, "floor": floor},
            uid=victim.job_uid,
        )
        self._record(
            preemptor.job_key, "shrink",
            {"victim": victim.job_key, "floor": floor},
            uid=preemptor.job_uid,
        )
        return True

    def _preempt_and_place_locked(
        self,
        new_res: Reservation,
        missing: Dict[str, int],
        ctx: GangContext,
        registered: bool = False,
    ) -> Optional[Dict[str, str]]:
        """Find the cheapest set of strictly-lower-priority victims whose
        eviction (or, with shrink_before_evict, shrink-to-floor) provably
        frees enough capacity, apply the plan, and place.  Victims are
        taken lowest priority first, youngest first within a priority
        (the least work is lost).  Shrinks are planned BEFORE evictions:
        an elastic victim degrades to its floor through its own drain ->
        checkpoint -> resume path instead of dying; only when every
        shrink still cannot fit the gang does full eviction start.  The
        whole plan is verified against a hypothetical free map BEFORE
        any pod or spec is touched: if even the maximal plan cannot fit
        the gang, nobody dies and nobody shrinks."""
        victims = sorted(
            (
                r for r in self._reservations.values()
                if r.priority < new_res.priority
            ),
            key=lambda r: (r.priority, -r.admitted_at, r.job_key),
        )
        if not victims:
            return None

        def free_with(
            evicts: List[Reservation],
            shrinks: List[Tuple[Reservation, Dict[str, str]]],
        ) -> Dict[str, int]:
            # the candidate's own placed/adopted members stay deducted:
            # offering their chips to the plan would double-count them
            # and land the gang over capacity.  A REGISTERED candidate
            # (resize path) is already priced by _free_locked; deducting
            # it again would undersell the cluster instead.
            hypo = (
                self._free_locked() if registered
                else self._free_for_candidate_locked(new_res)
            )
            for victim in evicts:
                for member, node in victim.assignments.items():
                    if node in hypo:
                        hypo[node] += victim.members.get(member, 0)
            for victim, drop in shrinks:
                for member, node in drop.items():
                    if node in hypo:
                        hypo[node] += victim.members.get(member, 0)
            return hypo

        evicts: List[Reservation] = []
        shrinks: List[Tuple[Reservation, Dict[str, str]]] = []
        placed = None
        if self.shrink_before_evict:
            for victim in victims:
                drop = self._shrink_drop_locked(victim)
                if not drop:
                    continue
                shrinks.append((victim, drop))
                placed = self._place_locked(
                    missing, free_with(evicts, shrinks), ctx
                )
                if placed is not None:
                    break
        if placed is None:
            for victim in victims:
                # a fully-evicted victim's shrink entry is superseded
                shrinks = [(v, d) for v, d in shrinks if v is not victim]
                evicts.append(victim)
                placed = self._place_locked(
                    missing, free_with(evicts, shrinks), ctx
                )
                if placed is not None:
                    break
        if placed is None:
            return None
        # prune non-contributing victims: the eligibility order is by
        # priority/age, not by where capacity is needed, so the plan may
        # include gangs whose chips the fit never uses — drop every
        # victim the plan still works without (shrinks first: a dropped
        # shrink is a gang not even degraded; each dropped eviction is a
        # whole gang NOT needlessly restarted)
        for victim, _drop in list(shrinks):
            trial = [(v, d) for v, d in shrinks if v is not victim]
            if self._place_locked(
                missing, free_with(evicts, trial), ctx
            ) is not None:
                shrinks = trial
        for victim in list(evicts):
            trial = [v for v in evicts if v is not victim]
            if self._place_locked(
                missing, free_with(trial, shrinks), ctx
            ) is not None:
                evicts = trial
        if shrinks:
            # shrink-ONLY this round, even when the proven plan mixes
            # shrinks and evictions: shrunk capacity frees later (the
            # victims' own drain -> resume transitions), so evicting now
            # and returning pending would leave the freed slices
            # UNRESERVED — the evicted gang's requeue could re-admit
            # into its own freed slice and be evicted again on every
            # retry.  Once the shrinks land, the retry re-plans: the
            # floored victims have nothing left to shrink, so the
            # remaining shortfall becomes a pure-eviction plan, which
            # evicts and places atomically under this same lock.
            for victim, _drop in shrinks:
                # best-effort: a failed spec patch (storm) just leaves
                # the gang pending; the retry re-plans on fresh state
                self._request_shrink_locked(victim, preemptor=new_res)
            return None
        for victim in evicts:
            if not self._evict_locked(victim, preemptor=new_res):
                # an eviction write failed (storm): abort with every
                # remaining reservation intact — already-killed members
                # restart into their victim's still-held slots, and the
                # new gang stays pending for the next sync's retry
                return None
        # re-place against the REAL free map now that victims are gone
        return self._place_locked(missing, free_with([], []), ctx)

    def _evict_locked(self, victim: Reservation, preemptor: Reservation
                      ) -> bool:
        """Kill every member pod of `victim` with SIGTERM semantics (exit
        143 — the graceful-drain code PR 3's restart accounting already
        books) and release its reservation.  All-or-nothing: any kill
        failure aborts BEFORE the release, so the victim's capacity is
        never freed while its pods still run."""
        killed: List[str] = []
        for member in sorted(victim.assignments):
            # kill the pod that EXISTS: a warm-claimed member's pod keeps
            # the standby's name, and killing the member name would miss
            # it (NotFound == "already gone") — leaving a live pod on
            # chips just handed to the preemptor
            if not self._kill_member(victim.namespace, victim.pod_of(member)):
                self.note(
                    f"preempt_abort job={victim.job_key} member={member}"
                )
                if killed:
                    self.evictions[victim.job_key] = (
                        self.evictions.get(victim.job_key, 0) + len(killed)
                    )
                return False
            killed.append(member)
        self._reservations.pop(victim.job_uid, None)
        self._mark_pending_locked(victim.job_uid, victim.job_key, victim.kind)
        self.evictions[victim.job_key] = (
            self.evictions.get(victim.job_key, 0)
            + len([m for m in killed if m])
        )
        metrics.SCHEDULER_PREEMPTIONS.inc({"policy": self.policy_name})
        try:
            self.cluster.record_event(
                {"kind": victim.kind,
                 "metadata": {"name": victim.job_key.partition("/")[2],
                              "namespace": victim.namespace}},
                "Warning", REASON_PREEMPTED,
                f"gang preempted by higher-priority "
                f"{preemptor.job_key} (priority {preemptor.priority} > "
                f"{victim.priority}); replicas sent SIGTERM",
            )
        except Exception:  # noqa: BLE001 — eventing is best-effort
            pass
        self.note(
            f"preempt gang={victim.job_key} members={len(killed)} "
            f"by={preemptor.job_key}"
        )
        # victim+beneficiary pair: the victim's timeline says who took
        # its capacity, the preemptor's says whose it took
        self._record(
            victim.job_key, "preempted",
            {"by": preemptor.job_key, "members": len(killed)},
            uid=victim.job_uid,
        )
        self._record(
            preemptor.job_key, "preemption",
            {"victim": victim.job_key, "members": len(killed)},
            uid=preemptor.job_uid,
        )
        return True

    def _record(self, job_key: str, event: str, detail: Dict[str, Any],
                uid: Optional[str] = None) -> None:
        """Flight-recorder seam: scheduler decisions stamped into the
        affected job's timeline (no-op when no recorder is wired)."""
        if self.recorder is not None:
            self.recorder.record(
                job_key, "scheduler", event, detail, uid=uid,
                ts=self.clock(),
            )

    def _kill_member(self, namespace: str, name: str) -> bool:
        """SIGTERM one member pod: phase Failed, exit 143.  A pod that
        does not exist (create still pending) or is already terminal
        counts as killed — there is nothing left to drain.  One
        conflict retry (a kubelet status write racing us); anything
        else is a real failure the caller must abort on."""
        for attempt in (0, 1):
            try:
                pod = self.cluster.get_pod(namespace, name)
            except NotFoundError:
                return True
            except (ApiError, OSError):
                return False
            if objects.pod_phase(pod) in (
                objects.POD_FAILED, objects.POD_SUCCEEDED
            ):
                return True
            containers = pod.get("spec", {}).get("containers", []) or [{}]
            cname = containers[0].get("name", "main")
            pod.setdefault("status", {})
            pod["status"]["phase"] = objects.POD_FAILED
            pod["status"]["reason"] = "Preempted"
            pod["status"]["containerStatuses"] = [{
                "name": cname,
                "state": {"terminated": {"exitCode": 143,
                                         "reason": "Preempted"}},
                "restartCount": 0,
            }]
            try:
                self.cluster.update_pod(pod)
                return True
            except NotFoundError:
                return True
            except ConflictError:
                if attempt == 1:
                    return False
                continue
            except (ApiError, OSError):
                return False
        return False

    # ------------------------------------------------------------------ drain
    def drain_node(self, node: str, kill: Callable[[str, str], bool]
                   ) -> int:
        """Node drain through the scheduler: every gang with at least one
        member reserved on `node` is evicted AS A UNIT (a TPU slice is
        unusable partially — members on other nodes die too) and its
        reservation released, so the gang re-enters admission wholesale.
        `kill` is the caller's pod-killer (the chaos injector's
        kill_pod, which books the kill and logs it into the seeded event
        stream); returns members killed.

        The node is CORDONED first: the evicted gangs requeue
        immediately, and without the cordon the very next admission
        could re-place them onto the node being drained (it has the
        most free chips by construction).  The cordon persists until an
        explicit uncordon() — the drain caller decides when the node is
        healthy again."""
        self.cordon(node)
        with self._lock:
            victims = sorted(
                (
                    res for res in self._reservations.values()
                    if node in res.assignments.values()
                ),
                key=lambda r: r.job_key,
            )
            n = 0
            for victim in victims:
                alive = []
                for member in sorted(victim.assignments):
                    # the caller's killer does its own restart
                    # bookkeeping (FaultInjector.retryable_kills) — the
                    # scheduler's eviction book stays preemption-only so
                    # the two tallies never double-count a drain.  Kill
                    # by ACTUAL pod name (warm claims keep the standby's)
                    pod_name = victim.pod_of(member)
                    if kill(victim.namespace, pod_name):
                        n += 1
                    if self._member_alive(victim.namespace, pod_name):
                        alive.append(member)
                if alive:
                    # a member survived the kill (Pending under injected
                    # pull latency, a conflicted status write): releasing
                    # now would offer a live pod's chips to the next gang
                    # — keep the reservation, exactly like the preemption
                    # path's abort; killed members restart into their
                    # still-held slots and the next drain retries
                    self.note(
                        f"drain_keep gang={victim.job_key} node={node} "
                        f"alive={len(alive)}"
                    )
                    continue
                self._reservations.pop(victim.job_uid, None)
                self._mark_pending_locked(victim.job_uid, victim.job_key, victim.kind)
                self.note(
                    f"drain_evict gang={victim.job_key} node={node} "
                    f"members={len(victim.assignments)}"
                )
                self._record(
                    victim.job_key, "drain_evicted",
                    {"node": node, "members": len(victim.assignments)},
                    uid=victim.job_uid,
                )
            self._update_gauges_locked()
            return n

    def _member_alive(self, namespace: str, pod_name: str) -> bool:
        """True while the pod exists in a non-terminal phase (Pending or
        Running) — i.e. it still occupies its chips.  Unreadable (storm)
        counts as alive: assuming dead under uncertainty frees capacity a
        live pod may hold."""
        try:
            pod = self.cluster.get_pod(namespace, pod_name)
        except NotFoundError:
            return False
        except (ApiError, OSError):
            return True
        return objects.is_pod_active(pod)

    def stop(self) -> None:
        try:
            self.cluster.unsubscribe("Node", self._on_node_event)
        except Exception:  # noqa: BLE001 — best-effort detach on shutdown
            pass
