"""Span tracing for the reconcile pipeline.

The reference logs reconcile durations as one opaque number
(controller.go:303-307); the port's reconcile-latency histogram says HOW
SLOW a sync was but not WHERE the time went. This module adds the missing
dimension: a thread-safe `Tracer` producing nested spans (name, attrs,
start, duration, parent), so each reconcile yields a phase breakdown —
expectation check vs pod reconcile vs service reconcile vs status rules.

Three consumers share one instrumentation point:
  - per-phase `Histogram`s (engine/metrics.py): `span(histogram=...)`
    observes the span duration on exit, so Prometheus gets
    `tpu_operator_sync_phase_duration_seconds{kind,phase}` for free;
  - Chrome trace-event JSON (`to_chrome_trace()`): load a dump in
    chrome://tracing / Perfetto to see syncs nested on a timeline;
  - the `/debug/traces` endpoint (cmd/health.py) and `--trace-dump`
    (cmd/main.py) serve/persist the same export.

Spans nest via a thread-local stack (each worker thread traces its own
sync); finished ROOT spans land in a bounded ring buffer, so a long-lived
operator keeps the most recent traces without unbounded growth.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class Span:
    """One timed region. `duration` stays None until the span finishes.

    `category` becomes the Chrome trace event's `cat` (the trace viewer's
    filter axis): reconcile spans and serving-request spans share one
    export but remain separable.  `thread_id` is the trace LANE, not
    necessarily an OS thread — serving telemetry assigns one virtual lane
    per request so overlapping in-flight requests render as parallel
    tracks instead of a single overdrawn row."""

    name: str
    start: float  # perf_counter seconds (duration arithmetic)
    wall_start: float  # epoch seconds (trace-viewer timestamps)
    attrs: Dict[str, Any] = field(default_factory=dict)
    duration: Optional[float] = None
    parent: Optional["Span"] = None
    children: List["Span"] = field(default_factory=list)
    thread_id: int = 0
    category: str = "reconcile"

    def walk(self) -> Iterator["Span"]:
        """Depth-first iteration over this span and all descendants."""
        yield self
        for child in self.children:
            yield from child.walk()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "start": self.wall_start,
            "duration": self.duration,
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }


class Tracer:
    """Thread-safe nested-span tracer.

    `span()` is the single entry point: it pushes onto the calling
    thread's stack (so spans opened inside an open span become children),
    and on exit either attaches to the parent or — for roots — lands in
    the shared ring buffer of finished traces. Passing `histogram=` (an
    engine.metrics.Histogram) observes the duration with `labels=` on
    exit, which is how per-phase histograms stay in lock-step with the
    trace without double instrumentation."""

    def __init__(self, max_traces: int = 256) -> None:
        self.max_traces = max_traces
        self._finished: "deque[Span]" = deque(maxlen=max_traces)
        self._local = threading.local()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- spans
    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _thread_id(self) -> int:
        # cached per thread: get_native_id() is a real syscall (gettid) and
        # spans are opened several times per sync — on hardened kernels the
        # uncached call was ~30% of reconcile CPU under profile
        tid = getattr(self._local, "tid", None)
        if tid is None:
            tid = self._local.tid = threading.get_native_id()
        return tid

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def span(
        self,
        name: str,
        attrs: Optional[Dict[str, Any]] = None,
        histogram=None,
        labels: Optional[Dict[str, str]] = None,
    ) -> Iterator[Span]:
        stack = self._stack()
        sp = Span(
            name=name,
            start=time.perf_counter(),
            wall_start=time.time(),
            attrs=dict(attrs or {}),
            parent=stack[-1] if stack else None,
            thread_id=self._thread_id(),
        )
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.duration = time.perf_counter() - sp.start
            stack.pop()
            if sp.parent is not None:
                sp.parent.children.append(sp)
            else:
                with self._lock:
                    self._finished.append(sp)
            if histogram is not None:
                histogram.observe(sp.duration, labels)

    def record(self, span: Span) -> None:
        """Land an externally assembled FINISHED root span in the ring
        buffer.  `span()` is the right tool for code-shaped regions; this
        is the seam for lifecycles that interleave — a serving request's
        queued/prefill/decode phases overlap other requests' phases on
        the same host thread, so a context-manager stack cannot express
        them and the caller builds the span tree itself."""
        if span.duration is None:
            raise ValueError(
                f"span {span.name!r} is unfinished (duration=None) — "
                f"record() takes completed root spans only")
        with self._lock:
            self._finished.append(span)

    # ------------------------------------------------------------ queries
    def traces(self) -> List[Span]:
        """Snapshot of finished root spans, oldest first."""
        with self._lock:
            return list(self._finished)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()

    # ------------------------------------------------------------- export
    def to_chrome_trace(
        self, category: Optional[str] = None, limit: Optional[int] = None
    ) -> Dict[str, Any]:
        """Chrome trace-event format (`ph:"X"` complete events, micros) —
        loadable in chrome://tracing and Perfetto.

        `category` keeps only spans whose `cat` matches (reconcile vs
        serving traces share one ring but are separable; /debug/traces
        additionally merges per-job "timeline" lanes and per-request
        "request" lanes under the same axis); `limit` keeps
        only the most recent N root traces — the /debug/traces query
        filters, so a dashboard can pull \"last 5 serving traces\" without
        downloading the whole ring.  With both given, the category
        filter runs FIRST: ?category=serving&limit=5 means the newest 5
        serving traces, not \"the newest 5 traces, serving spans only\"
        (which could be empty while serving traces sit in the ring)."""
        events: List[Dict[str, Any]] = []
        pid = os.getpid()
        roots = self.traces()
        if category is not None:
            roots = [
                r for r in roots
                if any(sp.category == category for sp in r.walk())
            ]
        if limit is not None and limit >= 0:
            roots = roots[-limit:] if limit > 0 else []
        for root in roots:
            for sp in root.walk():
                if sp.duration is None:
                    continue
                if category is not None and sp.category != category:
                    continue
                events.append(
                    {
                        "name": sp.name,
                        "cat": sp.category,
                        "ph": "X",
                        "ts": sp.wall_start * 1e6,
                        "dur": sp.duration * 1e6,
                        "pid": pid,
                        "tid": sp.thread_id,
                        "args": dict(sp.attrs),
                    }
                )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_json(
        self, category: Optional[str] = None, limit: Optional[int] = None
    ) -> str:
        return json.dumps(self.to_chrome_trace(category=category, limit=limit))

    def dump(self, path: str) -> None:
        """Write the Chrome trace-event JSON to `path` (--trace-dump)."""
        with open(path, "w") as fh:
            fh.write(self.export_chrome_json())


_GLOBAL = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer (the analogue of the metrics registry):
    engines default to it, the health server serves it, --trace-dump
    persists it."""
    return _GLOBAL
