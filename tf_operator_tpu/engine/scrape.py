"""Serving-fleet scrape transport — the REAL telemetry path.

PR 14's router and autoscaler consume replica telemetry through an
in-process push seam (`FleetAutoscaler.report()` / `FleetRouter.observe()`)
— perfect for simulation and tests, useless for a deployed front-end,
where telemetry arrives by scraping each replica's `/metrics` over HTTP
and every failure mode of that transport (timeouts, 5xx, half an
exposition, a dead listener) is a routine Tuesday.  This module is the
transport:

  - **`ScrapeLoop`**: per-replica HTTP GET of `/metrics` over the pooled
    keep-alive `HttpTransport` (PR 5 — one warm socket per replica
    endpoint, retired on any transport error), parsing the serving
    families every replica already exports (PR 9's block-pool gauges,
    the admission-blocked counter, the queue-wait histogram) and feeding
    the SAME `report()`/`observe()` calls the push seam would — push
    stays as the sim/test seam, asserted equivalent by
    tests/test_zscrape.py's push-vs-scrape test.
  - **Failure accounting**: every attempt lands in
    `serving_scrape_attempts_total{outcome}` (ok / timeout / http_error
    / truncated / error); failures back off per replica on PR 3's
    `capped_exponential` ladder and count toward the router's ejection
    threshold (`FleetRouter.scrape_failed` — a failing scrape IS a
    missed heartbeat).  Per-replica scrape AGE (seconds since the last
    success) is exported as `serving_scrape_age_seconds{replica}` and
    published into the fleet status doc `tpu-jobs describe` renders —
    age rising on every replica at once is the signature of the scrape
    plane (not the fleet) being down, which the router answers with its
    degraded round-robin fallback.
  - **Exposition parsing**: the queue-wait p99 source is the replica's
    `serving_queue_wait_seconds` histogram — per-scrape bucket-count
    deltas are resolved into samples at their bucket upper bound (the
    same ceil-rank read `bench.merge_bucket_percentiles` performs), so
    the autoscaler's sliding window sees the scrape exactly as it sees
    the push.  A 200 whose body is missing the block families is a
    TRUNCATED exposition and counts as a failed scrape — half an
    exposition must never feed half a decision.  Replica queue depth is
    not separately exported by serve_loop; the scrape reports the batch
    occupancy gauge as the in-flight level and 0 queue depth (the
    occupancy score's dominant term is free blocks; depth is a
    tie-break the push seam still carries exactly).

Wired behind `--serving-scrape-interval` / `--serving-scrape-timeout`
(cmd/options.py) and run by the manager beside `--serving-autoscale`
(cmd/manager.build_scrape_loop).  Target discovery reads each
TPUServingJob pod's `kubeflow.org/metrics-endpoint` annotation, falling
back to `status.podIP` + the SERVING_PORT env the ServingAdapter stamps.
"""
from __future__ import annotations

import dataclasses
import re
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from tf_operator_tpu.engine import metrics, servefleet
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.client import HttpTransport, KubeConfig
from tf_operator_tpu.k8s.informer import capped_exponential
from tf_operator_tpu.utils.logging import get_logger

log = get_logger("serving-scrape")

SERVING_KIND = "TPUServingJob"
# pod annotation naming the replica's metrics listener ("host:port" or a
# full http URL) — the explicit override; absent, discovery falls back
# to status.podIP + the SERVING_PORT env
METRICS_ENDPOINT_ANNOTATION = "kubeflow.org/metrics-endpoint"

# the serving families a replica scrape resolves (engine/metrics.py,
# fed by models/telemetry.py + serve_loop's paged pool)
F_BLOCKS_TOTAL = "tpu_operator_serving_kv_blocks_total"
F_BLOCKS_USED = "tpu_operator_serving_kv_blocks_used"
F_BLOCKED = "tpu_operator_serving_admission_blocked_on_memory_total"
F_OCCUPANCY = "tpu_operator_serving_batch_occupancy"
F_QUEUE_WAIT_BUCKET = "tpu_operator_serving_queue_wait_seconds_bucket"

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


class TruncatedExposition(Exception):
    """A 200 response whose body is missing the serving block families:
    the exposition was cut mid-flight (or the target is not a serving
    replica) — treated as a failed scrape, never as zeros."""


def parse_exposition(
    text: str,
) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Prometheus text exposition -> {family: [(labels, value), ...]}.
    Comment/TYPE/HELP lines are skipped; unparseable sample lines are
    ignored (a scraper must survive a family it does not know)."""
    families: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, rest = line.partition("{")
        labels: Dict[str, str] = {}
        if rest:
            raw, _, value_part = rest.rpartition("}")
            labels = {k: v for k, v in _LABEL_RE.findall(raw)}
        else:
            # split on the FIRST space: a legal trailing timestamp
            # ("name value ts") must not be taken as the value
            name, _, value_part = line.partition(" ")
            name = name.strip()
        try:
            value = float(value_part.strip().split()[0])
        except (ValueError, IndexError):
            continue
        families.setdefault(name.strip(), []).append((labels, value))
    return families


def _value(
    families: Dict[str, List[Tuple[Dict[str, str], float]]],
    name: str,
) -> Optional[float]:
    samples = families.get(name)
    if not samples:
        return None
    # prefer the unlabeled sample (the process-level level); fall back
    # to the first labeled one
    for labels, value in samples:
        if not labels:
            return value
    return samples[0][1]


def _bucket_counts(
    families: Dict[str, List[Tuple[Dict[str, str], float]]],
    name: str,
) -> Dict[float, float]:
    out: Dict[float, float] = {}
    for labels, value in families.get(name, ()):
        le = labels.get("le")
        if le is None:
            continue
        out[float("inf") if le == "+Inf" else float(le)] = value
    return out


def queue_wait_samples(
    buckets: Dict[float, float], prev: Dict[float, float]
) -> List[float]:
    """Resolve per-scrape cumulative-bucket deltas into wait samples at
    their bucket's upper bound (the ceil-rank read: a sample that landed
    in (le_{i-1}, le_i] is worth le_i — the same convention
    bench.merge_bucket_percentiles uses).  +Inf overflow clamps to the
    largest finite bound."""
    finite = sorted(le for le in buckets if le != float("inf"))
    samples: List[float] = []
    below = 0.0
    for le in finite:
        cum_delta = buckets[le] - prev.get(le, 0.0)
        n = int(round(cum_delta - below))
        if n > 0:
            samples.extend([le] * n)
        below = max(below, cum_delta)
    inf_delta = buckets.get(float("inf"), 0.0) - prev.get(
        float("inf"), 0.0
    )
    overflow = int(round(inf_delta - below))
    if finite and overflow > 0:
        samples.extend([finite[-1]] * overflow)
    return samples


@dataclasses.dataclass(frozen=True)
class ScrapeTarget:
    """One replica's scrape address."""

    job_key: str   # "<namespace>/<job name>"
    replica: str   # pod name (the router/autoscaler replica id)
    url: str       # full URL, e.g. "http://10.0.0.7:8000/metrics"


@dataclasses.dataclass
class ReplicaSample:
    """One successful scrape, in the shape report()/observe() take."""

    free_blocks: int = 0
    total_blocks: int = 0
    queue_depth: int = 0
    inflight: int = 0
    blocked_total: int = 0
    queue_waits: List[float] = dataclasses.field(default_factory=list)


def extract_sample(
    families: Dict[str, List[Tuple[Dict[str, str], float]]],
    prev_buckets: Dict[float, float],
) -> Tuple[ReplicaSample, Dict[float, float]]:
    """Families -> ReplicaSample (+ this scrape's bucket counts, the
    next scrape's delta baseline).  Raises TruncatedExposition when the
    block families are absent — the number the autoscaler scales on must
    never be fabricated from a cut-off body."""
    total = _value(families, F_BLOCKS_TOTAL)
    used = _value(families, F_BLOCKS_USED)
    if total is None or used is None:
        raise TruncatedExposition(
            f"exposition missing {F_BLOCKS_TOTAL}/{F_BLOCKS_USED}"
        )
    blocked = _value(families, F_BLOCKED) or 0.0
    occupancy = _value(families, F_OCCUPANCY) or 0.0
    buckets = _bucket_counts(families, F_QUEUE_WAIT_BUCKET)
    waits = queue_wait_samples(buckets, prev_buckets)
    return (
        ReplicaSample(
            free_blocks=max(0, int(total - used)),
            total_blocks=int(total),
            queue_depth=0,
            inflight=int(occupancy),
            blocked_total=int(blocked),
            queue_waits=waits,
        ),
        buckets,
    )


def discover_targets(cluster) -> List[ScrapeTarget]:
    """Scrape targets from the cluster: every pod controlled by a
    TPUServingJob whose metrics listener is discoverable — the
    `kubeflow.org/metrics-endpoint` annotation ("host:port" or full
    URL), else `status.podIP` + the SERVING_PORT env the ServingAdapter
    stamps on every replica."""
    out: List[ScrapeTarget] = []
    for pod in cluster.list("Pod"):
        ref = objects.get_controller_of(pod)
        if not ref or ref.get("kind") != SERVING_KIND:
            continue
        md = pod.get("metadata") or {}
        status = pod.get("status") or {}
        # a terminated-but-lingering pod (OOM kill, eviction) or one
        # already being deleted is not a scrape target: its podIP may
        # outlive its listener, and scraping it forever would pin a
        # rising age series + endless scrape_failed() for a replica
        # that can never recover
        if md.get("deletionTimestamp") or status.get("phase") in (
            "Succeeded", "Failed",
        ):
            continue
        endpoint = (md.get("annotations") or {}).get(
            METRICS_ENDPOINT_ANNOTATION
        )
        if not endpoint:
            ip = status.get("podIP")
            port = None
            for c in (pod.get("spec") or {}).get("containers", []) or []:
                for e in c.get("env", []) or []:
                    if e.get("name") == "SERVING_PORT":
                        port = e.get("value")
                        break
                if port:
                    # FIRST container wins — the ServingAdapter stamps
                    # the serving container first; a sidecar's copy of
                    # the env must not steal the scrape target
                    break
            if ip and port:
                endpoint = f"{ip}:{port}"
        if not endpoint:
            continue
        base = (
            endpoint if endpoint.startswith(("http://", "https://"))
            else f"http://{endpoint}"
        ).rstrip("/")
        # a full-URL annotation may already name the metrics path
        url = base if base.endswith("/metrics") else f"{base}/metrics"
        out.append(ScrapeTarget(
            job_key=f"{objects.namespace_of(pod)}/{ref.get('name', '')}",
            replica=objects.name_of(pod),
            url=url,
        ))
    return sorted(out, key=lambda t: (t.job_key, t.replica))


class _TargetState:
    __slots__ = (
        "failures", "next_due", "last_success", "first_seen", "buckets",
        "primed",
    )

    def __init__(self, now: float) -> None:
        self.failures = 0
        self.next_due = now
        self.last_success: Optional[float] = None
        self.first_seen = now
        # previous scrape's cumulative queue-wait buckets (delta base)
        self.buckets: Dict[float, float] = {}
        # False until the first successful scrape: that scrape's
        # cumulative histogram is the replica's lifetime history, not
        # this interval's traffic — baseline only, never samples
        self.primed = False


class ScrapeLoop:
    """The per-replica /metrics scrape driver.  See module docs.

    `targets` is a callable returning the current List[ScrapeTarget]
    (re-evaluated every tick, so replicas appear/disappear with the
    fleet); `autoscaler` receives report() per successful scrape;
    `router_of(job_key)` (optional — a colocated front-end) returns the
    FleetRouter whose observe()/scrape_failed() mirror the telemetry.
    FleetRouter is NOT thread-safe: a front-end wiring router_of while
    serving requests on its own thread must serialize router calls
    (one lock or one event loop) — the started loop calls the router
    from its scrape thread."""

    def __init__(
        self,
        targets: Callable[[], List[ScrapeTarget]],
        autoscaler=None,
        router_of: Optional[Callable[[str], Any]] = None,
        interval: float = 1.0,
        timeout: float = 2.0,
        clock: Callable[[], float] = time.time,
        backoff_max_s: float = 30.0,
        transport_factory: Optional[Callable] = None,
        reqrecorder=None,
    ) -> None:
        self.targets = targets
        self.autoscaler = autoscaler
        self.router_of = router_of
        # request recorder (engine/reqtrace.py) whose SLO windows tick
        # with the scrape cadence — burn rates must decay when traffic
        # stops, not freeze at their last fed value
        self.reqrecorder = reqrecorder
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.clock = clock
        self.backoff_max_s = float(backoff_max_s)
        self.transport_factory = transport_factory
        self._transports: Dict[str, HttpTransport] = {}
        self._transport_lock = threading.Lock()
        self._state: Dict[Tuple[str, str], _TargetState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # persistent fetch pool (lazily built, regrown on fleet growth):
        # a tick per second spawning-and-joining N fresh OS threads is
        # pure churn in a long-lived operator process
        self._fetch_pool: Optional[ThreadPoolExecutor] = None
        self._fetch_pool_size = 0
        # fetches abandoned at the wall deadline whose worker is still
        # wedged mid-body (a slow-drip response): at most ONE per
        # target — no new fetch is stacked on a wedged one, so a sick
        # replica parks exactly one worker, never the whole pool
        self._stuck: Dict[Tuple[str, str], Any] = {}

    # ------------------------------------------------------------ transport
    def _base_of(self, url: str) -> Tuple[str, str]:
        """Scrape URL -> (scheme://netloc, request path).  A real URL
        split, not a substring hunt: a hostname containing "metrics"
        ("http://metrics-gw:9090/metrics") or a path-bearing endpoint
        ("http://10.0.0.7:9000/custom/metrics") must dial the right
        host and GET the right path."""
        parts = urlsplit(url)
        path = parts.path or "/metrics"
        if parts.query:
            path = f"{path}?{parts.query}"
        return f"{parts.scheme}://{parts.netloc}", path

    def _fetcher(self, n: int) -> ThreadPoolExecutor:
        """The persistent fetch pool, regrown when the fleet outgrows it
        (an executor's worker count is fixed at creation; a storm tick
        must still run every timing-out fetch concurrently or one slow
        replica serializes its siblings' cadence behind its timeout)."""
        if self._fetch_pool is None or self._fetch_pool_size < n:
            if self._fetch_pool is not None:
                self._fetch_pool.shutdown(wait=False)
            self._fetch_pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="serving-scrape-fetch"
            )
            self._fetch_pool_size = n
        return self._fetch_pool

    def _transport(self, base: str) -> HttpTransport:
        with self._transport_lock:
            t = self._transports.get(base)
            if t is None:
                cfg = KubeConfig(server=base)
                if self.transport_factory is not None:
                    t = self.transport_factory(cfg, self.timeout)
                else:
                    # small pool: one warm keep-alive socket per replica
                    # is the steady state; 2 covers a retire mid-burst
                    t = HttpTransport(
                        cfg, timeout=self.timeout, pool_size=2
                    )
                self._transports[base] = t
            return t

    # -------------------------------------------------------------- scraping
    def _fetch(
        self, target: ScrapeTarget
    ) -> Tuple[str, Optional[int], Optional[str]]:
        """The HTTP half of one scrape: ("response", status, body) or a
        terminal outcome.  Safe to run concurrently — it touches only
        the locked transport pool."""
        base, path = self._base_of(target.url)
        try:
            status, body, _headers = self._transport(base).request(
                "GET", path
            )
        except TimeoutError:
            return ("timeout", None, None)
        except Exception:  # noqa: BLE001 — any transport death is a miss
            return ("error", None, None)
        return ("response", status, body if isinstance(body, str) else "")

    def scrape_one(
        self,
        target: ScrapeTarget,
        fetched: Optional[Tuple[str, Optional[int], Optional[str]]] = None,
    ) -> str:
        """One scrape attempt -> outcome label (ok / timeout /
        http_error / truncated / error).  Feeds the autoscaler + router
        on ok; failures only count.  `fetched` carries the concurrent
        fetch phase's result; absent, the GET runs inline."""
        state = self._state[(target.job_key, target.replica)]
        kind, status, body = (
            fetched if fetched is not None else self._fetch(target)
        )
        if kind != "response":
            return kind
        if status != 200:
            return "http_error"
        try:
            sample, buckets = extract_sample(
                parse_exposition(body or ""), state.buckets
            )
        except TruncatedExposition:
            return "truncated"
        state.buckets = buckets
        if not state.primed:
            # an operator (re)start against a long-running replica must
            # not replay its whole histogram into the scale-out window
            state.primed = True
            sample.queue_waits = []
        now = self.clock()
        if self.autoscaler is not None:
            self.autoscaler.report(
                target.job_key, target.replica,
                free_blocks=sample.free_blocks,
                total_blocks=sample.total_blocks,
                queue_depth=sample.queue_depth,
                inflight=sample.inflight,
                blocked_total=sample.blocked_total,
                queue_waits=sample.queue_waits,
                ts=now,
            )
        router = (
            self.router_of(target.job_key)
            if self.router_of is not None else None
        )
        if router is not None:
            router.observe(
                target.replica, sample.free_blocks, sample.total_blocks,
                sample.queue_depth,
            )
        return "ok"

    def _finish_scrape(
        self,
        target: ScrapeTarget,
        fetched: Tuple[str, Optional[int], Optional[str]],
    ) -> int:
        """Parse/feed one fetched scrape and book its outcome (attempt
        counter, backoff ladder, router failure signal).  Returns 1 on
        an ok scrape, 0 otherwise."""
        key = (target.job_key, target.replica)
        state = self._state[key]
        outcome = self.scrape_one(target, fetched)
        metrics.SERVING_SCRAPE_ATTEMPTS.inc({"outcome": outcome})
        now = self.clock()
        if outcome == "ok":
            state.failures = 0
            state.last_success = now
            state.next_due = now + self.interval
            return 1
        state.failures += 1
        # first failure retries at the base interval; the ladder climbs
        # from the second on (same 0-based exponent every other backoff
        # in this codebase uses)
        state.next_due = now + capped_exponential(
            self.interval, state.failures - 1, self.backoff_max_s
        )
        router = (
            self.router_of(target.job_key)
            if self.router_of is not None else None
        )
        if router is not None:
            router.scrape_failed(target.replica)
        return 0

    def tick(self) -> int:
        """Scrape every due target once; returns the success count.
        Exports per-replica scrape age and publishes it into the fleet
        status doc afterward, success or not — age is the signal."""
        now = self.clock()
        targets = self.targets()
        known = {(t.job_key, t.replica) for t in targets}
        for key in [k for k in self._state if k not in known]:
            del self._state[key]
            self._stuck.pop(key, None)
            servefleet.drop_scrape(*key)
            # a replica that left the fleet must stop exporting: a
            # frozen age series would trip the staleness alert forever
            metrics.SERVING_SCRAPE_AGE.remove(
                {"serving_job": key[0], "replica": key[1]}
            )
        # ...and its warm keep-alive transport must close: over fleet
        # churn every departed pod IP would otherwise pin sockets in
        # this long-lived process forever
        live_bases = {self._base_of(t.url)[0] for t in targets}
        with self._transport_lock:
            for base in [
                b for b in self._transports if b not in live_bases
            ]:
                self._transports.pop(base).close()
        due = []
        for target in targets:
            key = (target.job_key, target.replica)
            state = self._state.get(key)
            if state is None:
                state = self._state[key] = _TargetState(now)
            if now >= state.next_due and not self._stop.is_set():
                due.append(target)
        # fetch phase runs CONCURRENTLY and results are processed in
        # COMPLETION order: in a storm, one timing-out (or slow-DRIP)
        # replica must not hold a healthy sibling's already-arrived
        # sample hostage to the shared deadline — healthy telemetry
        # feeds the instant its fetch lands.  Parsing + feeding still
        # run on THIS thread; per-replica sample order is unchanged
        # (the deterministic surface is the push seam, not wall-clock
        # transport timing).
        ok = 0
        submit = []
        for t in due:
            key = (t.job_key, t.replica)
            prev = self._stuck.get(key)
            if prev is not None:
                if prev.done():
                    self._stuck.pop(key)  # late result discarded
                else:
                    # the previous attempt is still wedged mid-body: do
                    # not stack another worker on it — the attempt still
                    # counts (backoff climbs, scrape_failed fires) but
                    # the sick replica holds exactly one worker
                    ok += self._finish_scrape(t, ("timeout", None, None))
                    continue
            submit.append(t)
        if submit:
            # capacity covers the new fetches PLUS the parked workers,
            # so healthy siblings never queue behind a wedged fetch
            pool = self._fetcher(len(submit) + len(self._stuck))
            by_future = {
                pool.submit(self._fetch, t): t for t in submit
            }
            # shared wall deadline: the per-recv socket timeout does
            # NOT bound a slow-DRIP response (every recv succeeds, the
            # body never ends) — an unbounded wait would let one sick
            # replica stall every healthy sibling's cadence and blow
            # past stop()'s join bound.  An abandoned fetch's worker
            # finishes (or trickles) on its own; its late result is
            # discarded.
            try:
                for fut in as_completed(
                    by_future, timeout=self.timeout + 1.0
                ):
                    ok += self._finish_scrape(
                        by_future.pop(fut), fut.result()
                    )
            except FuturesTimeout:
                pass
            for fut, target in by_future.items():  # abandoned at deadline
                key = (target.job_key, target.replica)
                self._stuck[key] = fut
                ok += self._finish_scrape(target, ("timeout", None, None))
        now = self.clock()  # the fetch phase consumed wall time
        for target in targets:
            state = self._state[(target.job_key, target.replica)]
            age = now - (
                state.last_success
                if state.last_success is not None else state.first_seen
            )
            metrics.SERVING_SCRAPE_AGE.set(
                age,
                {"serving_job": target.job_key,
                 "replica": target.replica},
            )
            servefleet.note_scrape(
                target.job_key, target.replica, age, state.failures
            )
        if self.reqrecorder is not None and self.reqrecorder.enabled:
            self.reqrecorder.slo_tick(now)
        return ok

    def scrape_age(self, job_key: str, replica: str) -> Optional[float]:
        state = self._state.get((job_key, replica))
        if state is None:
            return None
        anchor = (
            state.last_success
            if state.last_success is not None else state.first_seen
        )
        return self.clock() - anchor

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is not None:
            if self._thread.is_alive():
                return
            # a previous stop() timed out its join and left the thread
            # recorded; it has since drained and exited on the stop
            # event — reap it, or the loop could never be restarted
            # (silent no-op: ages frozen, autoscaler blind)
            self._thread = None
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serving-scrape", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            # a storm tick is bounded by the HTTP timeout (fetches run
            # concurrently): join past that bound rather than closing a
            # live tick's sockets underneath it
            t.join(timeout=self.timeout + self.interval + 1.0)
            if t.is_alive():
                # the daemon thread did not drain in time — leave its
                # transports alone (it would only re-dial them) and
                # keep _thread set so start() refuses while it lives
                return
            self._thread = None
        with self._transport_lock:
            for tr in self._transports.values():
                tr.close()
            self._transports.clear()
        if self._fetch_pool is not None:
            self._fetch_pool.shutdown(wait=False)
            self._fetch_pool = None
            self._fetch_pool_size = 0
        self._stuck.clear()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a storm must not kill the loop
                # ...but a silently swallowed tick is an invisible
                # outage: the autoscaler runs blind while the operator
                # looks healthy.  Log it so the failure is diagnosable.
                log.exception("scrape tick failed")
