"""Slow-start control fan-out — client-go's slowStartBatch for this engine.

The reference issues pod/service creates through kubeflow/common's
CreatePodsWithControllerRef, which ultimately rides client-go's
`slowStartBatch` (kubernetes pkg/controller/controller_utils.go): operations
run in concurrent batches that grow exponentially — 1, 2, 4, ... — so a
healthy apiserver quickly reaches full parallelism while a failing one is
probed with a single cheap request instead of a thundering herd of N
doomed creates.  This module is that algorithm, parameterized by the
`--control-fanout` cap:

  - ``fanout <= 1`` is the SERIAL path: every op runs inline on the calling
    thread, in list order, exactly like the pre-fan-out engine — no threads
    are ever created, so deterministic harnesses (the seeded chaos soak,
    single-threaded test dispatch) replay byte-identically.
  - ``fanout > 1`` dispatches each batch on short-lived worker threads,
    batch size capped at ``fanout``.  With ``abort_on_failure`` (the create
    path), a batch containing any failure stops the ramp: in-flight ops of
    that batch complete, remaining ops are never attempted — client-go
    semantics, so one quota denial costs O(batch) requests, not O(N).
    Teardown paths pass ``abort_on_failure=False``: every delete is
    attempted regardless of earlier failures (one stuck pod must not leave
    the rest of a slice running), only the parallelism changes.

Expectations accounting is the caller's contract: each op raises its own
expectation immediately before its API call and lowers it on failure (the
same raise/lower pairing the serial engine always had), so ops that are
never attempted never touch expectations, and `satisfied_expectations`
stays exact under partial failure.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence, Tuple

from tf_operator_tpu.engine import metrics

SLOW_START_INITIAL_BATCH_SIZE = 1  # client-go SlowStartInitialBatchSize

# One shared worker pool for every fan-out dispatch in the process:
# batches are joined inside each slow_start_batch call, so the per-call
# concurrency bound is the batch size (<= fanout), not the pool size —
# sharing only amortizes thread creation, which would otherwise be paid
# per batch, per sync.  The pool bounds TOTAL fan-out concurrency across
# concurrent syncs; a fanout above it still completes, just no wider.
_MAX_FANOUT_WORKERS = 64
_executor: Optional[ThreadPoolExecutor] = None
_executor_lock = threading.Lock()


def _shared_executor() -> ThreadPoolExecutor:
    global _executor
    with _executor_lock:
        if _executor is None:
            _executor = ThreadPoolExecutor(
                max_workers=_MAX_FANOUT_WORKERS,
                thread_name_prefix="control-fanout",
            )
        return _executor


@dataclass
class FanoutResult:
    """Outcome of one slow_start_batch run.

    ``failures`` carries (op index, exception) for every attempted op that
    raised; ``attempted`` counts ops that ran (successes + failures) — ops
    past an abort were never started and appear in neither."""

    successes: int = 0
    attempted: int = 0
    failures: List[Tuple[int, BaseException]] = field(default_factory=list)

    @property
    def first_error(self) -> Optional[BaseException]:
        if not self.failures:
            return None
        return min(self.failures, key=lambda f: f[0])[1]

    def raise_first(self) -> None:
        err = self.first_error
        if err is not None:
            raise err


def slow_start_batch(
    ops: Sequence[Callable[[], Any]],
    fanout: int,
    abort_on_failure: bool = True,
    observe: Optional[Callable[[int], None]] = None,
) -> FanoutResult:
    """Run ``ops`` with exponential batch growth capped at ``fanout``.

    ``observe`` (when given) receives each dispatched batch's size — the
    hook the engine points at the fan-out batch-size histogram."""
    result = FanoutResult()
    if not ops:
        return result
    if observe is None:
        observe = lambda n: metrics.CONTROL_FANOUT_BATCH.observe(n)  # noqa: E731

    if fanout <= 1:
        # serial fast path: no threads, strict list order, first failure
        # aborts (or not) exactly like the batched path with batch size 1
        for i, op in enumerate(ops):
            observe(1)
            result.attempted += 1
            try:
                op()
                result.successes += 1
            except Exception as e:  # noqa: BLE001 — collected for the caller
                result.failures.append((i, e))
                if abort_on_failure:
                    break
        return result

    pos = 0
    batch = SLOW_START_INITIAL_BATCH_SIZE
    lock = threading.Lock()
    while pos < len(ops):
        size = min(batch, fanout, len(ops) - pos)
        observe(size)
        batch_failed = False

        def run_one(index: int) -> None:
            nonlocal batch_failed
            try:
                ops[index]()
                with lock:
                    result.successes += 1
            except Exception as e:  # noqa: BLE001 — collected for the caller
                with lock:
                    result.failures.append((index, e))
                    batch_failed = True

        result.attempted += size
        if size == 1:
            run_one(pos)
        else:
            futures = [
                _shared_executor().submit(run_one, pos + j)
                for j in range(size)
            ]
            for f in futures:
                f.result()  # run_one never raises; this is the join
        pos += size
        if batch_failed and abort_on_failure:
            break
        batch *= 2
    result.failures.sort(key=lambda f: f[0])
    return result
