"""Job flight recorder — one causal, bounded timeline per job.

Six subsystems now make decisions about a job (sharded control plane,
cluster scheduler, warm pool, control fan-out, chaos harness, fencing),
and their evidence lands in six disconnected places: metrics are
aggregates, the seeded chaos log is cluster-wide, Events are lossy
prose.  Nobody can answer "why did job X take 90s to reach Running?"
without grepping all of them.  This module is the missing join: every
subsystem appends structured, monotonically-sequenced records to ONE
per-job ring, so the whole causal chain — informer receipt, workqueue
wait, sync phase breakdown, gang admission / preemption, warm-pool
claim, fan-out batch, fencing rejection, crash-loop backoff, injected
chaos fault — reads as a single ordered story per job.

Design constraints, in order:

  - **Bounded**: per job, one ring (``deque(maxlen=events_per_job)``)
    for routine traffic (informer / workqueue / sync) and one for
    DECISIONS (scheduler / warm pool / fencing / chaos / condition
    transitions) — merged by sequence on read.  Routine chatter must
    not evict the rare records that explain it: a job parked pending
    for an hour churns hundreds of requeue/sync records, and a single
    shared ring would forget the one gang_pending record that explains
    the hour.  At most ``max_jobs`` jobs are tracked; past the cap the
    least-recently-touched FINISHED job is evicted (live jobs never
    are — their count is bounded by the cluster, and dropping a live
    timeline would be answering "why is this job slow" with "we threw
    that away").
  - **Cheap on the hot path**: append is O(1) under the JOB's ring lock;
    the recorder-wide directory lock is taken only on first contact with
    a job (and on eviction), never per record — N worker threads
    recording N different jobs do not serialize on each other.
  - **Causal**: records carry a per-job monotonic ``seq`` assigned under
    the ring lock, so cross-thread appends to one job have a total
    order; the workqueue stamps a correlation id at enqueue that the
    dequeue record and the sync's span bridge both carry, tying "waited
    1.2s in the queue" to "then spent 40ms in pod_reconcile".
  - **Derived SLOs**: milestones observed while recording feed the
    ``tpu_operator_job_time_to_scheduled_seconds`` /
    ``_time_to_running_seconds`` / ``_restart_mttr_seconds`` histograms
    from per-job ground truth (first gang admission / first Running
    condition / failure-to-Running repair), not inferred from aggregate
    counters.

One recorder per operator process, shared by every shard's engines (like
the scheduler and warm pool): slot failover moves a job between shards
without losing or duplicating its timeline.  ``events_per_job=0``
disables recording entirely — every seam checks ``recorder is None`` or
finds ``record()`` returning immediately, and the chaos goldens stay
byte-identical either way (the recorder never writes to the seeded log).

Served as JSON at ``/debug/timeline/<ns>/<name>`` (cmd/health.py),
rendered by ``tpu-jobs timeline NS NAME``, and merged into the
``/debug/traces`` Chrome-trace export as one lane per job.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from tf_operator_tpu.engine import metrics

# (source, event) pairs that mark the "scheduled" milestone: the cluster
# scheduler's bind when one is running, otherwise the first pod create /
# warm claim (placement and creation coincide without a scheduler).
_SCHEDULED_MARKS = frozenset({
    ("scheduler", "gang_admitted"),
    ("controller", "pods_created"),
    ("warmpool", "warm_claim"),
})
# Sources whose records are DECISIONS (scheduler binds/preemptions, warm
# claims, fencing rejections, chaos injections, condition transitions,
# ownership moves) vs routine high-frequency traffic (informer
# deliveries, queue stamps, sync bridges).  Each class gets its own ring:
# a job parked pending for an hour churns hundreds of requeue/sync
# records, and one shared ring would evict the single gang_pending
# record that explains the hour — the flight recorder would forget
# exactly what it exists to remember.
_DECISION_SOURCES = frozenset({
    "scheduler", "warmpool", "fencing", "chaos", "shard", "controller",
    # fleet autoscaler (engine/servefleet.py): scale_out / scale_in /
    # replica_drained — the records that explain why a serving fleet
    # changed shape, each carrying the trigger metric and its value
    "servefleet",
    # fleet router (models/router.py): router_degraded / router_recovered
    # / replica_ejected / replica_readmitted / hedge_issued — the
    # records that explain why dispatch changed shape under failure,
    # each carrying the trigger metric, observed value, and threshold
    "router",
    # SLO burn-rate engine (engine/reqtrace.py): slo_burn — both burn
    # windows of a latency axis crossed the configured threshold, the
    # record carrying the axis, window burn rates, and observed p99
    "slo",
})
# controller events that are routine cadence, not decisions: a job
# parked in a long crash-loop backoff window re-records its wait every
# sync, and routing that into the decision ring would let the chatter
# evict the restart/condition records that explain it.
_ROUTINE_OVERRIDES = frozenset({("controller", "restart_backoff")})
# Chrome-trace lane ids for job timelines start here — far above any
# plausible native thread id, so merged exports never alias a real
# worker thread's row to a job lane.
_LANE_TID_BASE = 1 << 24
# events that start the repair clock (MTTR) — the earliest failure
# evidence wins: an injected kill precedes the Restarting condition the
# controller stamps once it observes the dead pod.  The durable
# `restart` record is in the set too: a partially-degraded job (one of
# N workers dead) can keep its Running condition through the whole
# incident, so neither a Restarting transition nor a chaos record may
# exist — but every counted restart IS a failure, persisted.
_FAILURE_MARKS = frozenset({"kill", "preempted", "drain_evicted", "restart"})


class _JobTimeline:
    """One job's ring + SLO bookkeeping, guarded by its own lock."""

    __slots__ = (
        "key", "uid", "lock", "events", "decisions", "seq", "last_ts",
        "finished", "created_ts", "scheduled_ts", "running_ts",
        "restart_since", "mttr_last", "resize_since", "resize_last",
    )

    def __init__(self, key: str, cap: int) -> None:
        self.key = key
        self.uid: Optional[str] = None
        self.lock = threading.Lock()
        # two rings, one sequence: routine traffic (informer/workqueue/
        # sync) cannot evict the rare decision records that explain it
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self.decisions: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self.seq = 0
        self.last_ts = 0.0
        self.finished = False
        self.created_ts: Optional[float] = None
        self.scheduled_ts: Optional[float] = None
        self.running_ts: Optional[float] = None
        self.restart_since: Optional[float] = None
        self.mttr_last: Optional[float] = None
        self.resize_since: Optional[float] = None
        self.resize_last: Optional[float] = None

    def reset_locked(self, uid: Optional[str], ts: float) -> None:
        """A new incarnation (same ns/name, new UID) starts a fresh ring;
        seq keeps counting so ordering across the boundary stays total."""
        self.uid = uid
        self.events.clear()
        self.decisions.clear()
        self.finished = False
        self.created_ts = ts
        self.scheduled_ts = None
        self.running_ts = None
        self.restart_since = None
        self.mttr_last = None
        self.resize_since = None
        self.resize_last = None


class FlightRecorder:
    """Thread-safe bounded per-job flight recorder.  See module docs."""

    def __init__(
        self,
        events_per_job: int = 256,
        max_jobs: int = 1000,
        clock=time.time,
    ) -> None:
        self.events_per_job = int(events_per_job)
        self.max_jobs = max(1, int(max_jobs))
        self.clock = clock
        self._jobs: Dict[str, _JobTimeline] = {}
        # directory lock: first-contact admission + eviction ONLY — the
        # per-record hot path reads the dict without it (GIL-atomic) and
        # synchronizes on the job's own ring lock
        self._dir_lock = threading.Lock()
        self._corr = itertools.count(1)

    @property
    def enabled(self) -> bool:
        return self.events_per_job > 0

    def next_corr(self) -> int:
        """A fresh correlation id (stamped at workqueue enqueue, carried
        by the dequeue record and the sync's span bridge)."""
        return next(self._corr)

    # --------------------------------------------------------------- record
    def record(
        self,
        job_key: str,
        source: str,
        event: str,
        detail: Optional[Dict[str, Any]] = None,
        uid: Optional[str] = None,
        ts: Optional[float] = None,
    ) -> None:
        """Append one structured record to `job_key`'s ring.  O(1) under
        the job's ring lock; a disabled recorder returns immediately so
        every call site can stay unconditional behind a None check."""
        if self.events_per_job <= 0 or not job_key:
            return
        if ts is None:
            ts = self.clock()
        while True:
            tl = self._jobs.get(job_key)
            if tl is None:
                tl = self._admit(job_key)
            with tl.lock:
                if self._jobs.get(job_key) is not tl:
                    # lost a race with _evict_locked between the lookup
                    # and the lock: appending to the orphaned ring would
                    # silently drop the record — re-admit and retry
                    continue
                if uid:
                    if tl.uid is None:
                        tl.uid = uid
                    elif uid != tl.uid:
                        tl.reset_locked(uid, ts)
                tl.seq += 1
                ring = (
                    tl.decisions
                    if source in _DECISION_SOURCES
                    and (source, event) not in _ROUTINE_OVERRIDES
                    else tl.events
                )
                ring.append({
                    "seq": tl.seq,
                    "t": ts,
                    "source": source,
                    "event": event,
                    "detail": detail or {},
                })
                tl.last_ts = ts
                self._derive_locked(tl, source, event, detail or {}, ts)
            break
        metrics.JOB_TIMELINE_EVENTS.inc({"source": source})

    def record_sync(
        self, job_key: str, root_span, corr: Optional[int] = None,
        uid: Optional[str] = None,
    ) -> None:
        """Bridge one finished reconcile root span (engine/tracing.py)
        into the timeline: total duration + per-phase breakdown, tied to
        the workqueue's correlation id."""
        if self.events_per_job <= 0 or root_span is None:
            return
        phases: Dict[str, float] = {}
        for child in root_span.children:
            if child.duration is not None:
                phases[child.name] = (
                    phases.get(child.name, 0.0) + child.duration
                )
        detail: Dict[str, Any] = {
            "duration": round(root_span.duration or 0.0, 6),
            "phases": {k: round(v, 6) for k, v in phases.items()},
        }
        if corr is not None:
            detail["corr"] = corr
        self.record(job_key, "sync", "reconcile", detail, uid=uid)

    def finish(self, job_key: str) -> None:
        """Mark a job's timeline finished (deleted / terminal): it keeps
        serving reads but becomes eligible for LRU eviction."""
        for _ in range(2):
            tl = self._jobs.get(job_key)
            if tl is None:
                return
            with tl.lock:
                if self._jobs.get(job_key) is tl:
                    tl.finished = True
                    return
            # evicted-and-readmitted under us: mark the current entry
            # (one retry suffices — a second race leaves at worst an
            # unfinished ring the next finish() call closes)

    # ------------------------------------------------------------ directory
    def _admit(self, job_key: str) -> _JobTimeline:
        with self._dir_lock:
            tl = self._jobs.get(job_key)
            if tl is not None:
                return tl
            if len(self._jobs) >= self.max_jobs:
                self._evict_locked()
            tl = _JobTimeline(job_key, self.events_per_job)
            self._jobs[job_key] = tl
            return tl

    def _evict_locked(self) -> None:
        """Evict the least-recently-touched FINISHED job.  Live jobs are
        never evicted: if every tracked job is live the cap is allowed to
        stretch — live-job count is bounded by the cluster itself, and a
        silent hole in a live timeline is worse than the memory."""
        victim_key = None
        victim_ts = None
        for key, tl in self._jobs.items():
            if tl.finished and (victim_ts is None or tl.last_ts < victim_ts):
                victim_key, victim_ts = key, tl.last_ts
        if victim_key is not None:
            # delete UNDER the victim's ring lock: record()'s identity
            # re-check (is the dict entry still this object?) runs under
            # the same lock, so an append either lands before the
            # eviction (and is evicted with the finished job) or observes
            # the removal and re-admits a fresh ring — never into an
            # orphan.  Ordering is acyclic: dir_lock -> ring lock here,
            # and record() never takes dir_lock while holding a ring
            # lock (_admit runs before the ring lock is taken).
            with self._jobs[victim_key].lock:
                del self._jobs[victim_key]
            metrics.JOB_TIMELINE_EVICTIONS.inc()

    # -------------------------------------------------------------- derive
    def _derive_locked(
        self, tl: _JobTimeline, source: str, event: str,
        detail: Dict[str, Any], ts: float,
    ) -> None:
        if tl.created_ts is None:
            tl.created_ts = ts
        if (source, event) in _SCHEDULED_MARKS and tl.scheduled_ts is None:
            tl.scheduled_ts = ts
            metrics.JOB_TIME_TO_SCHEDULED.observe(
                max(0.0, ts - tl.created_ts)
            )
        if source == "controller" and event == "condition":
            ctype = detail.get("type")
            if ctype == "Running":
                if tl.running_ts is None:
                    tl.running_ts = ts
                    if tl.scheduled_ts is None:
                        # backstop: a storm can swallow the create-side
                        # milestone record (the sync that created the
                        # pods raised before recording) — a job that is
                        # RUNNING was necessarily scheduled, so the
                        # milestone is stamped no later than here
                        tl.scheduled_ts = ts
                        metrics.JOB_TIME_TO_SCHEDULED.observe(
                            max(0.0, ts - tl.created_ts)
                        )
                    metrics.JOB_TIME_TO_RUNNING.observe(
                        max(0.0, ts - tl.created_ts)
                    )
                if tl.restart_since is not None:
                    tl.mttr_last = max(0.0, ts - tl.restart_since)
                    tl.restart_since = None
                    metrics.JOB_RESTART_MTTR.observe(tl.mttr_last)
            elif ctype in ("Succeeded", "Failed"):
                tl.finished = True
            elif ctype == "Restarting" and tl.restart_since is None:
                tl.restart_since = ts
        elif source == "controller" and event == "resize_requested":
            # a retargeted resize (new generation mid-transition) keeps
            # the ORIGINAL start: the user-visible disruption began then
            if tl.resize_since is None:
                tl.resize_since = ts
        elif source == "controller" and event == "resumed":
            if tl.resize_since is not None:
                tl.resize_last = max(0.0, ts - tl.resize_since)
                tl.resize_since = None
                metrics.JOB_RESIZE_DURATION.observe(tl.resize_last)
        elif source == "controller" and event == "reverted":
            # only a FINAL revert (cancelled before drain) ends the
            # transition: an admission revert is transient — the
            # controller keeps retrying and the eventual `resumed` must
            # still observe the full requested->resumed duration
            if detail.get("final"):
                tl.resize_since = None
        elif source == "controller" and event == "replicas_active":
            # repair complete: every desired replica active again — the
            # close that works even when a partially-degraded job kept
            # its Running condition through the whole incident
            if tl.restart_since is not None:
                tl.mttr_last = max(0.0, ts - tl.restart_since)
                tl.restart_since = None
                metrics.JOB_RESTART_MTTR.observe(tl.mttr_last)
        elif event in _FAILURE_MARKS and tl.restart_since is None:
            tl.restart_since = ts

    @staticmethod
    def _slo_locked(tl: _JobTimeline) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if tl.created_ts is not None:
            if tl.scheduled_ts is not None:
                out["time_to_scheduled_s"] = round(
                    tl.scheduled_ts - tl.created_ts, 6
                )
            if tl.running_ts is not None:
                out["time_to_running_s"] = round(
                    tl.running_ts - tl.created_ts, 6
                )
        if tl.mttr_last is not None:
            out["last_restart_mttr_s"] = round(tl.mttr_last, 6)
        if tl.restart_since is not None:
            out["repair_in_progress_since"] = tl.restart_since
        if tl.resize_last is not None:
            out["last_resize_duration_s"] = round(tl.resize_last, 6)
        if tl.resize_since is not None:
            out["resize_in_progress_since"] = tl.resize_since
        return out

    # --------------------------------------------------------------- reads
    def jobs(self) -> List[str]:
        with self._dir_lock:
            return sorted(self._jobs)

    @staticmethod
    def _merged_locked(tl: _JobTimeline) -> List[Dict[str, Any]]:
        """Both rings interleaved back into one sequence (caller holds
        tl.lock) — the single merge every export shares."""
        return sorted(
            (dict(e) for e in (*tl.events, *tl.decisions)),
            key=lambda e: e["seq"],
        )

    def timeline(self, job_key: str) -> Optional[Dict[str, Any]]:
        """Snapshot of one job's timeline as a JSON-ready dict, or None
        when the job was never recorded (or has been evicted)."""
        tl = self._jobs.get(job_key)
        if tl is None:
            return None
        with tl.lock:
            return {
                "job": tl.key,
                "uid": tl.uid,
                "finished": tl.finished,
                "slo": self._slo_locked(tl),
                "events": self._merged_locked(tl),
            }

    def slo(self, job_key: str) -> Optional[Dict[str, Any]]:
        tl = self._jobs.get(job_key)
        if tl is None:
            return None
        with tl.lock:
            return self._slo_locked(tl)

    def to_dict(self) -> Dict[str, Any]:
        """Every live timeline (the SIGUSR1 / --trace-dump payload)."""
        return {
            "jobs": {
                key: tl for key in self.jobs()
                if (tl := self.timeline(key)) is not None
            }
        }

    def dump(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh)

    # -------------------------------------------------------------- export
    def chrome_events(
        self, per_job: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        """One Chrome-trace lane per job, merged into /debug/traces
        beside the reconcile/serving spans (cat "timeline"): records with
        a duration (sync bridges) render as complete events, the rest as
        instants, and each lane is named after its job.  `per_job` keeps
        only each lane's newest N records — ?limit=N must bound the
        recorder's contribution too, not just the tracer's roots."""
        events: List[Dict[str, Any]] = []
        pid = os.getpid()
        with self._dir_lock:
            items = sorted(self._jobs.items())
        # job lanes live in their own tid block far above real native
        # thread ids: a lane colliding with a worker thread's tid would
        # render that thread's reconcile spans inside a row labeled as a
        # job timeline in the merged export
        for lane, (key, tl) in enumerate(items, start=_LANE_TID_BASE + 1):
            with tl.lock:
                snapshot = self._merged_locked(tl)
            if per_job is not None and per_job >= 0:
                snapshot = snapshot[-per_job:] if per_job > 0 else []
            if not snapshot:
                # no records survive the cap: no lane either — a limit
                # meant to shrink the response must not still ship one
                # metadata row per tracked job
                continue
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": lane,
                "args": {"name": f"job {key}"},
            })
            for e in snapshot:
                args = {"source": e["source"], "seq": e["seq"],
                        **(e["detail"] or {})}
                dur = (e["detail"] or {}).get("duration")
                base = {
                    "name": e["event"], "cat": "timeline",
                    "ts": e["t"] * 1e6, "pid": pid, "tid": lane,
                    "args": args,
                }
                if isinstance(dur, (int, float)) and dur > 0:
                    # records are stamped at the moment they happen —
                    # for a sync bridge that is the sync's END — so the
                    # complete event starts dur earlier, aligning the
                    # job-lane bar with the tracer's span for the same
                    # sync in the merged export
                    events.append({
                        **base, "ph": "X", "ts": (e["t"] - dur) * 1e6,
                        "dur": dur * 1e6,
                    })
                else:
                    events.append({**base, "ph": "i", "s": "t"})
        return events


# disabled until an operator configures one (cmd/manager.build_recorder):
# the fallback the health endpoints and in-process CLI read when no
# explicit recorder was injected — mirrors tracing.get_tracer()
_GLOBAL = FlightRecorder(events_per_job=0)


def get_recorder() -> FlightRecorder:
    return _GLOBAL


def set_recorder(recorder: FlightRecorder) -> None:
    """Register the process's recorder (one per process, like the
    scheduler and warm pool) so /debug endpoints and the in-process CLI
    find it without explicit wiring."""
    global _GLOBAL
    _GLOBAL = recorder
