"""FrameworkAdapter — the per-framework callback set the engine drives.

This is the Python shape of the reference's ControllerInterface
(kubeflow/common; overridden methods at reference tfjob_controller.go:
SetClusterSpec :540, IsMasterRole :586, UpdateJobStatus :351, plus the
api-level defaults/validation). One adapter per job kind; registered in
controllers/registry.py (reference register_controller.go:36-49).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from tf_operator_tpu.api import common
from tf_operator_tpu.api.job import Job


class FrameworkAdapter:
    KIND: str = "Job"
    PLURAL: str = "jobs"
    REPLICA_TYPES: List[str] = []
    CONTAINER_NAME: str = ""
    PORT_NAME: str = ""
    DEFAULT_PORT: int = 0

    # ---- api-level hooks --------------------------------------------------
    def from_dict(self, d: Dict[str, Any]) -> Job:
        raise NotImplementedError

    def set_defaults(self, job: Job) -> None:
        raise NotImplementedError

    def validate(self, job: Job) -> None:
        raise NotImplementedError

    # ---- reconcile-time hooks --------------------------------------------
    def set_cluster_spec(
        self, job: Job, pod_template: Dict[str, Any], rtype: str, index: int
    ) -> None:
        """Inject cluster-discovery env (TF_CONFIG / MASTER_ADDR / DMLC_* /
        JAX coordinator) into the pod template. The reference's seam is
        SetClusterSpec (tfjob_controller.go:540-573)."""
        raise NotImplementedError

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        """Whether this replica gets the job-role=master label
        (reference tfjob_controller.go:586-593)."""
        return False

    def replica_order(self, replicas: Dict[str, common.ReplicaSpec]) -> List[str]:
        """Deterministic replica-type iteration order for status updates
        (reference status.go:95-101 orders Chief,Evaluator,Master,PS,Worker)."""
        return sorted(replicas.keys())

    def update_job_status(self, engine, job: Job, ctx: "StatusContext") -> None:
        """Framework success/running/failed condition rules, applied after
        per-replica pod reconciliation. Default: master-style semantics
        shared by PyTorch/XGBoost (success when the master-role replica
        type completes)."""
        raise NotImplementedError


class StatusContext:
    """What update_job_status gets to look at: the declared replicas and the
    freshly-counted pod states, plus an event recorder."""

    def __init__(
        self,
        replicas: Dict[str, common.ReplicaSpec],
        status: common.JobStatus,
        pods: List[Dict[str, Any]],
        now: str,
        record_event,
        restarted_types: Optional[set] = None,
    ) -> None:
        self.replicas = replicas
        self.status = status
        self.pods = pods
        self.now = now
        self.record_event = record_event
        # replica types the ENGINE deleted-for-restart in THIS sync; the
        # authoritative "is restarting" signal (the Restarting *condition*
        # lingers across syncs and conflates old restarts with new permanent
        # failures — the reference's wedge, status.go:186-196)
        self.restarted_types = restarted_types or set()

    def counts(self, rtype: str):
        rs = self.status.replica_statuses.get(rtype, common.ReplicaStatus())
        spec = self.replicas[rtype]
        expected = (spec.replicas or 0) - rs.succeeded
        return expected, rs.active, rs.succeeded, rs.failed
