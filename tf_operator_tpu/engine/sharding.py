"""Job-to-shard assignment — rendezvous hashing over shard slots.

The control plane scales past one process by partitioning jobs across N
shard *slots*; each slot is owned by exactly one controller worker at a
time (a per-slot ``coordination.k8s.io/Lease``, cmd/leader.py LeaseLock)
and every informer event is routed to the owning shard's workqueue.  The
partition function lives here, separate from the lease machinery, because
its only job is to be **stable**: every shard, standby, and zombie must
compute the same owner for the same job UID or two workqueues drive the
same job.

Rendezvous (highest-random-weight) hashing is used instead of a modulo
ring: changing the slot count from N to N±1 reassigns only ~1/N of the
keys (the keys whose top-scoring slot is the added/removed one), and
removing a slot moves *exactly* that slot's keys and no others — the
property the resize test asserts.  Scores come from blake2b, which is
stable across processes and Python versions (``hash()`` is salted per
process and would split the brain by construction).
"""
from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional

# Fencing-token annotation stamped into status-subresource write bodies by
# a sharded engine and checked by the stores (k8s/fake.py, and through it
# the REST façade + http apiserver).  Token format:
#   "<lease-namespace>/<lease-name>:<generation>"
# The store compares the token's generation against the named Lease's
# spec.generation and rejects older tokens with 403 — a zombie shard that
# wakes up after failover can never clobber the new owner's writes.  The
# annotation never persists: the status subresource merges .status only.
FENCE_ANNOTATION = "kubeflow.org/fencing-token"


def fence_token(namespace: str, name: str, generation: int) -> str:
    return f"{namespace}/{name}:{generation}"


# Default prefix of the per-slot Lease names.  Exposed here (not in
# cmd/manager.py) because it is CROSS-PROCESS shared state: every worker
# process, the supervisor's liveness view, and the bench's failover probe
# must derive the same Lease name for the same slot or they coordinate
# about different objects.
DEFAULT_LOCK_PREFIX = "tpu-operator-shard"


def shard_lock_name(slot: int, prefix: str = DEFAULT_LOCK_PREFIX) -> str:
    """Name of the Lease object guarding shard slot `slot` — the single
    naming rule shared by owners, standbys, zombies, and probes."""
    return f"{prefix}-{slot}"


def parse_fence_token(token: str) -> Optional[tuple]:
    """(namespace, name, generation) or None for an unparsable token."""
    ref, sep, gen = token.rpartition(":")
    if not sep:
        return None
    ns, _, name = ref.partition("/")
    try:
        return ns, name, int(gen)
    except ValueError:
        return None


def rendezvous_score(uid: str, slot: int) -> int:
    """Stable 64-bit score of (uid, slot).  One digest per pair — the
    route is recomputed per event, so the digest is kept cheap (blake2b
    with an 8-byte digest is a single short hash call)."""
    h = hashlib.blake2b(f"{slot}\x00{uid}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class ShardRouter:
    """Maps a job UID to its owning shard slot via rendezvous hashing.

    Slots are dense integers [0, n).  The router is pure and shared by
    every shard (and by standbys, and by the bench's failover probe):
    ownership *changes* are a lease concern; the slot a UID belongs to is
    a function of (uid, slot count) alone.
    """

    _MEMO_CAP = 65536

    def __init__(self, n_slots: int) -> None:
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        self.slots: List[int] = list(range(n_slots))
        # uid -> slot memo: every shard checks ownership of every event,
        # so one routing decision is consulted N times per event — the
        # hashes are cheap but not N-shards-times-per-event cheap.  Plain
        # dict ops are atomic under the GIL; the cap bounds a pathological
        # churn of unique UIDs (cleared wholesale, recomputed on demand).
        self._memo: dict = {}

    @property
    def n_slots(self) -> int:
        return len(self.slots)

    def slot_for(self, uid: Optional[str]) -> int:
        """Owning slot for a job UID.  A missing UID (malformed object)
        deterministically lands on slot 0 so it is still driven by exactly
        one shard rather than dropped by all of them."""
        if not uid:
            return 0
        if len(self.slots) == 1:
            return self.slots[0]
        slot = self._memo.get(uid)
        if slot is None:
            # max() tiebreak on (score, slot) keeps the choice total-ordered
            slot = max(
                self.slots, key=lambda s: (rendezvous_score(uid, s), s)
            )
            if len(self._memo) >= self._MEMO_CAP:
                self._memo.clear()
            self._memo[uid] = slot
        return slot

    def partition(self, uids: Iterable[str]) -> dict:
        """slot -> [uids] (bench + re-adopt sweeps)."""
        out: dict = {s: [] for s in self.slots}
        for uid in uids:
            out[self.slot_for(uid)].append(uid)
        return out
