"""Serving-fleet autoscaler — telemetry-driven scale-out/in for TPUServingJob.

The data plane (serve_loop + paged KV pool) exports exactly the signals
a fleet controller needs, and this module is the loop that acts on them:

  - **Scale OUT** when requests are visibly waiting on capacity:
    fleet queue-wait p99 over a sliding window crosses
    `scaleOutQueueWaitP99S`, or `serving_admission_blocked_on_memory`
    grew by >= `scaleOutBlockedAdmissions` since the last tick (the
    memory gate is parking admissions — more replicas is the only fix
    short of more HBM).  The action is a +1 replicas patch on the CR;
    the engine's ordinary create path then claims a warm-pool standby
    (PR 7), so reaction time is one claim latency, not an image pull.
  - **Scale IN** when the fleet pays for memory nobody uses: KV-block
    occupancy (used/total across replicas) stays under
    `scaleInOccupancyFloor` with no queue pressure.  Scale-in is
    TWO-PHASE so no request is ever dropped: the victim (always the
    highest-indexed replica — the one the engine's scale-down delete
    will take) is first marked draining (`kubeflow.org/fleet-drain`
    annotation; the router stops dispatching to it), and only once its
    in-flight count reads zero is the replicas count patched down —
    `replica_drained` lands on the timeline between `scale_in` and the
    pod delete.

Every action is a DECISIONS record on the owning TPUServingJob's
timeline (source `servefleet`, detail carrying the trigger metric and
its observed value vs threshold), so `tpu-jobs timeline` explains every
autoscale the way it already explains every preemption.

`AutoscalePolicy` is the pure decision function — no cluster, no
threads — shared verbatim by the operator loop here and the
deterministic fleet simulation (models/fleetsim.py) that `make
bench-fleet` and the seeded chaos tests drive, so the benched policy IS
the shipped policy.

Telemetry transport: replicas push via `FleetAutoscaler.report()` — the
in-process stand-in for scraping each replica's /metrics (the families
exist; the scrape loop is deployment plumbing).  A process-global
fleet-status registry feeds `tpu-jobs describe`'s fleet section.
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from tf_operator_tpu.api import servingjob as servingapi
from tf_operator_tpu.engine import metrics
from tf_operator_tpu.k8s import objects

# CR annotation naming replicas the router must stop dispatching to (a
# JSON list of pod names): the coordination channel between the
# operator-side autoscaler and the serving-side router — a front-end
# router applies it via FleetRouter.sync_drains(drain_targets(job)) on
# CR watch events (the fleet harness/in-process hook short-circuits it)
DRAIN_ANNOTATION = "kubeflow.org/fleet-drain"

_QUEUE_WAIT_WINDOW_S = 30.0


def ceil_rank_percentile(samples: List[float], q: float) -> float:
    """Ceil-rank percentile over raw samples (q in (0, 1]) — THE one
    quantile convention shared by the autoscaler's queue-wait p99 and
    the fleet simulation's scoring, so the benched policy and the
    shipped policy cannot silently diverge on what 'p99' means.
    Returns 0.0 for an empty sample set."""
    if not samples:
        return 0.0
    ordered = sorted(samples)
    rank = -(-int(q * 100) * len(ordered) // 100)
    return ordered[min(max(rank, 1), len(ordered)) - 1]


def drain_targets(job: Dict[str, Any]) -> List[str]:
    """Parse a TPUServingJob's fleet-drain annotation into the replica
    names the router must stop dispatching to (the read side of
    DRAIN_ANNOTATION; malformed/absent reads as empty)."""
    ann = (job.get("metadata") or {}).get("annotations") or {}
    try:
        targets = json.loads(ann.get(DRAIN_ANNOTATION, "[]"))
    except ValueError:
        return []
    return [t for t in targets if isinstance(t, str)] if isinstance(
        targets, list) else []


@dataclasses.dataclass
class ReplicaTelemetry:
    """One replica's most recent report (its own serving families)."""

    free_blocks: int = 0
    total_blocks: int = 0
    queue_depth: int = 0
    inflight: int = 0
    blocked_total: int = 0  # cumulative admission_blocked_on_memory_total
    ts: float = 0.0


@dataclasses.dataclass
class ScaleDecision:
    direction: Optional[str] = None  # "out" | "in" | None
    trigger: str = ""                # metric family that fired
    value: float = 0.0
    threshold: float = 0.0

    @property
    def detail(self) -> Dict[str, Any]:
        return {
            "trigger": self.trigger,
            "value": round(self.value, 4),
            "threshold": self.threshold,
        }


class AutoscalePolicy:
    """The pure scale decision: thresholds in, direction out.  Stateless
    except for the cooldown clocks — shared by the operator loop and the
    fleet simulation so both act identically on the same telemetry.

    Cooldowns are DIRECTION-AWARE, the standard autoscaler asymmetry:
    scale-out repeats quickly (a burst needs the whole ramp now; the cap
    is maxReplicas, and overshoot costs idle replicas for seconds),
    scale-in waits long (tearing a replica down re-queues nothing but
    re-claiming one costs a warm standby — flapping is pure waste)."""

    def __init__(
        self,
        spec: servingapi.AutoscaleSpec,
        out_cooldown_s: float = 1.0,
        in_cooldown_s: float = 10.0,
        cooldown_s: Optional[float] = None,
    ) -> None:
        self.spec = spec
        if cooldown_s is not None:  # symmetric override (tests)
            out_cooldown_s = in_cooldown_s = cooldown_s
        self.out_cooldown_s = float(out_cooldown_s)
        self.in_cooldown_s = float(in_cooldown_s)
        self._cooldown_until = 0.0

    def decide(
        self,
        now: float,
        replicas: int,
        queue_wait_p99_s: float,
        blocked_delta: int,
        occupancy: Optional[float],
    ) -> ScaleDecision:
        """`occupancy` None means NO block telemetry exists (no replica
        has reported) — unknown, not idle: scale-in is vetoed, because
        draining a fleet whose scrape loop is down would shrink a
        possibly-saturated fleet to minReplicas on zero evidence."""
        s = self.spec
        if now < self._cooldown_until:
            return ScaleDecision()
        if replicas < s.max_replicas:
            if queue_wait_p99_s > s.scale_out_queue_wait_p99_s:
                return ScaleDecision(
                    "out", "serving_queue_wait_seconds_p99",
                    queue_wait_p99_s, s.scale_out_queue_wait_p99_s,
                )
            if blocked_delta >= s.scale_out_blocked_admissions:
                return ScaleDecision(
                    "out", "serving_admission_blocked_on_memory_total",
                    float(blocked_delta),
                    float(s.scale_out_blocked_admissions),
                )
        if (
            occupancy is not None
            and replicas > s.min_replicas
            and occupancy < s.scale_in_occupancy_floor
            and blocked_delta == 0
            and queue_wait_p99_s <= s.scale_out_queue_wait_p99_s / 2.0
        ):
            # under the floor AND no queue pressure: one replica's worth
            # of capacity is idle memory
            return ScaleDecision(
                "in", "serving_kv_block_occupancy",
                occupancy, s.scale_in_occupancy_floor,
            )
        return ScaleDecision()

    def acted(self, now: float, direction: str = "in") -> None:
        cool = (
            self.out_cooldown_s if direction == "out" else self.in_cooldown_s
        )
        self._cooldown_until = now + cool


class DisaggAutoscalePolicy:
    """Per-fleet scale decisions for disaggregated serving: the two
    tiers saturate on DIFFERENT axes, which is the whole reason to
    split them (ISSUE 20) — a unified fleet's autoscaler conflates
    prefill pressure (requests waiting for a prompt slot) with decode
    pressure (lanes camping on KV blocks) and scales the wrong
    dimension.  Here:

      * PREFILL scales on queue-wait p99 — a prefill replica's pool
        turns over per prompt, so memory is never the binding
        constraint; waiting requests are.  Scale-in when the queue is
        quiet (p99 under half the out threshold).
      * DECODE scales on KV-block occupancy and blocked admissions —
        decode lanes hold blocks for the whole generation, so the
        fleet saturates in memory long before compute.  Scale-in under
        the occupancy floor with no blocked admissions.

    Same cooldown asymmetry as AutoscalePolicy, tracked PER FLEET (a
    prefill burst must not put the decode tier on cooldown).  Both
    deciders are pure: thresholds in, direction out — shared verbatim
    by the fleet simulation (models/fleetsim.DisaggHarness) and the
    operator loop, the same no-divergence contract as
    ceil_rank_percentile."""

    def __init__(
        self,
        spec: servingapi.AutoscaleSpec,
        out_cooldown_s: float = 1.0,
        in_cooldown_s: float = 10.0,
    ) -> None:
        self.spec = spec
        self.out_cooldown_s = float(out_cooldown_s)
        self.in_cooldown_s = float(in_cooldown_s)
        self._cooldown_until = {"prefill": 0.0, "decode": 0.0}

    def decide_prefill(
        self,
        now: float,
        replicas: int,
        queue_wait_p99_s: float,
    ) -> ScaleDecision:
        s = self.spec
        if now < self._cooldown_until["prefill"]:
            return ScaleDecision()
        if (replicas < s.max_replicas
                and queue_wait_p99_s > s.scale_out_queue_wait_p99_s):
            return ScaleDecision(
                "out", "serving_queue_wait_seconds_p99",
                queue_wait_p99_s, s.scale_out_queue_wait_p99_s,
            )
        if (replicas > s.min_replicas
                and queue_wait_p99_s
                <= s.scale_out_queue_wait_p99_s / 2.0):
            return ScaleDecision(
                "in", "serving_queue_wait_seconds_p99",
                queue_wait_p99_s, s.scale_out_queue_wait_p99_s / 2.0,
            )
        return ScaleDecision()

    def decide_decode(
        self,
        now: float,
        replicas: int,
        occupancy: Optional[float],
        blocked_delta: int,
    ) -> ScaleDecision:
        """`occupancy` None = no decode replica has reported — unknown,
        not idle: scale-in vetoed (same evidence rule as
        AutoscalePolicy.decide)."""
        s = self.spec
        if now < self._cooldown_until["decode"]:
            return ScaleDecision()
        if replicas < s.max_replicas:
            if blocked_delta >= s.scale_out_blocked_admissions:
                return ScaleDecision(
                    "out", "serving_admission_blocked_on_memory_total",
                    float(blocked_delta),
                    float(s.scale_out_blocked_admissions),
                )
            if (occupancy is not None
                    and occupancy > 1.0 - (1.0 -
                                           s.scale_in_occupancy_floor)
                    / 2.0):
                # nearly full: handoffs are about to start bouncing
                # (serving_handoff_retries_total) — scale before the
                # retry storm, not after
                return ScaleDecision(
                    "out", "serving_kv_block_occupancy",
                    occupancy,
                    1.0 - (1.0 - s.scale_in_occupancy_floor) / 2.0,
                )
        if (
            occupancy is not None
            and replicas > s.min_replicas
            and occupancy < s.scale_in_occupancy_floor
            and blocked_delta == 0
        ):
            return ScaleDecision(
                "in", "serving_kv_block_occupancy",
                occupancy, s.scale_in_occupancy_floor,
            )
        return ScaleDecision()

    def acted(self, now: float, fleet: str,
              direction: str = "in") -> None:
        cool = (
            self.out_cooldown_s if direction == "out"
            else self.in_cooldown_s
        )
        self._cooldown_until[fleet] = now + cool


# --------------------------------------------------------------------------
# process-global fleet status (CLI describe's fleet section) — mirrors
# timeline.get_recorder(): the operator process registers, readers fall
# back to "nothing known" cleanly
# --------------------------------------------------------------------------
_STATUS_LOCK = threading.Lock()
_FLEET_STATUS: Dict[str, Dict[str, Any]] = {}


def fleet_status(job_key: str) -> Optional[Dict[str, Any]]:
    with _STATUS_LOCK:
        doc = _FLEET_STATUS.get(job_key)
        return json.loads(json.dumps(doc)) if doc is not None else None


def _set_fleet_status(job_key: str, doc: Dict[str, Any]) -> None:
    with _STATUS_LOCK:
        _FLEET_STATUS[job_key] = doc


def _drop_fleet_status(job_key: str) -> None:
    with _STATUS_LOCK:
        _FLEET_STATUS.pop(job_key, None)


def reset_fleet_status() -> None:
    """Test isolation hook."""
    with _STATUS_LOCK:
        _FLEET_STATUS.clear()


def note_scrape(job_key: str, replica: str, age_s: float,
                failures: int) -> None:
    """The scrape loop's contribution to the fleet status doc: each
    replica's scrape age and consecutive-failure count, rendered by the
    describe Fleet section.  Absent entirely when no scrape loop runs —
    describe output stays byte-identical."""
    with _STATUS_LOCK:
        doc = _FLEET_STATUS.setdefault(job_key, {})
        doc.setdefault("scrape", {})[replica] = {
            "age_s": round(age_s, 3), "failures": int(failures),
        }


def drop_scrape(job_key: str, replica: str) -> None:
    with _STATUS_LOCK:
        (_FLEET_STATUS.get(job_key) or {}).get("scrape", {}).pop(
            replica, None
        )


def note_router_state(job_key: str, degraded: bool,
                      ejected: List[str]) -> None:
    """The router's contribution: fleet-wide degraded flag and the
    currently-ejected replica set.  Only a router with an owning job key
    publishes (a front-end process / the fleet harness)."""
    with _STATUS_LOCK:
        doc = _FLEET_STATUS.setdefault(job_key, {})
        doc["degraded"] = bool(degraded)
        doc["ejected"] = sorted(ejected)


class FleetAutoscaler:
    """The operator half: watches TPUServingJobs, aggregates per-replica
    telemetry, and edits `spec.servingReplicaSpecs.Replica.replicas`.
    One per process (the coordinator's loop; shards never run their own —
    two autoscalers patching one CR would fight the cooldown)."""

    KIND = servingapi.KIND

    def __init__(
        self,
        cluster,
        interval: float = 1.0,
        clock: Callable[[], float] = time.time,
        recorder=None,
        cooldown_s: Optional[float] = None,
        drain_timeout_s: float = 30.0,
        reqrecorder=None,
    ) -> None:
        self.cluster = cluster
        self.interval = float(interval)
        self.clock = clock
        self.recorder = recorder
        # request recorder (engine/reqtrace.py): each tick pushes every
        # job's `spec.slo` into it so the burn-rate engine always judges
        # against the CURRENT spec, and clears it when the spec drops it
        self.reqrecorder = reqrecorder
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None else max(5.0, 2 * interval)
        )
        # how long a drain may wait on a victim that stopped reporting:
        # a victim that died permanently mid-drain (exit 1 — never
        # replaced, never reports again) must not wedge autoscaling for
        # the job forever; past the timeout the drain completes on the
        # evidence available (a dead replica has nothing in flight to
        # protect — pod-level recovery is the ExitCode machinery's job)
        self.drain_timeout_s = float(drain_timeout_s)
        # job key -> replica name -> latest report
        self._telemetry: Dict[str, Dict[str, ReplicaTelemetry]] = {}
        # job key -> sliding window of (ts, queue_wait_s) samples
        self._queue_waits: Dict[str, "deque"] = {}
        # job key -> replica -> blocked_total at the previous tick
        self._blocked_prev: Dict[str, Dict[str, int]] = {}
        self._policies: Dict[str, AutoscalePolicy] = {}
        # job key -> replica currently draining toward a -1 patch
        self._draining: Dict[str, str] = {}
        self._lock = threading.Lock()
        # job keys seen on the previous tick: a key that disappears was
        # deleted — its telemetry/policy/status state is garbage-collected
        # (without this, state for deleted jobs persists for the
        # operator's lifetime)
        self._known: set = set()
        # job key -> when the current drain began (the timeout anchor)
        self._drain_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # optional coupling hooks for an in-process router (the fleet
        # harness / a colocated front-end); the annotation remains the
        # cross-process channel
        self.on_drain: Optional[Callable[[str, str], None]] = None
        self.inflight_of: Optional[Callable[[str, str], int]] = None

    # ------------------------------------------------------------ telemetry
    def report(
        self,
        job_key: str,
        replica: str,
        free_blocks: int = 0,
        total_blocks: int = 0,
        queue_depth: int = 0,
        inflight: int = 0,
        blocked_total: int = 0,
        queue_waits: Optional[List[float]] = None,
        ts: Optional[float] = None,
    ) -> None:
        """One replica's telemetry push (the scrape stand-in).
        `queue_waits` carries the queue-wait seconds of requests admitted
        since the replica's last report — the p99 source."""
        now = self.clock() if ts is None else ts
        with self._lock:
            self._telemetry.setdefault(job_key, {})[replica] = (
                ReplicaTelemetry(
                    free_blocks=int(free_blocks),
                    total_blocks=int(total_blocks),
                    queue_depth=int(queue_depth),
                    inflight=int(inflight),
                    blocked_total=int(blocked_total),
                    ts=now,
                )
            )
            window = self._queue_waits.setdefault(
                job_key, deque(maxlen=4096)
            )
            for w in queue_waits or ():
                window.append((now, float(w)))

    def forget(self, job_key: str) -> None:
        with self._lock:
            self._telemetry.pop(job_key, None)
            self._queue_waits.pop(job_key, None)
            self._blocked_prev.pop(job_key, None)
            self._policies.pop(job_key, None)
            self._draining.pop(job_key, None)
            self._drain_since.pop(job_key, None)
        if self.reqrecorder is not None:
            self.reqrecorder.set_slo(job_key, None)
        _drop_fleet_status(job_key)

    def _queue_wait_p99(self, job_key: str, now: float) -> float:
        window = self._queue_waits.get(job_key)
        if not window:
            return 0.0
        while window and now - window[0][0] > _QUEUE_WAIT_WINDOW_S:
            window.popleft()
        return ceil_rank_percentile([w for _, w in window], 0.99)

    def _blocked_delta(self, job_key: str, tele: Dict[str, ReplicaTelemetry]) -> int:
        prev = self._blocked_prev.setdefault(job_key, {})
        delta = 0
        for rid, t in tele.items():
            delta += max(0, t.blocked_total - prev.get(rid, 0))
            prev[rid] = t.blocked_total
        for rid in list(prev):
            if rid not in tele:
                del prev[rid]
        return delta

    # -------------------------------------------------------------- control
    def tick(self) -> None:
        """One autoscale pass over every TPUServingJob in scope; state
        for jobs that disappeared since the last pass is dropped."""
        try:
            jobs = self.cluster.list(self.KIND)
        except Exception:  # noqa: BLE001 — storm; next tick retries
            return
        seen = set()
        for job in jobs:
            md = job.get("metadata") or {}
            seen.add(f"{objects.namespace_of(job)}/{md.get('name', '')}")
            try:
                self._tick_job(job)
            except Exception:  # noqa: BLE001 — conflict/storm on one job
                continue       # must not starve the others; next tick retries
        for gone in self._known - seen:
            self.forget(gone)
        self._known = seen

    @staticmethod
    def _replicas_of(job: Dict[str, Any]) -> Optional[int]:
        spec = (job.get("spec") or {}).get("servingReplicaSpecs") or {}
        replica = spec.get(servingapi.REPLICA_REPLICA) or {}
        return replica.get("replicas")

    def _patch_replicas(self, job: Dict[str, Any], count: int,
                        drain: Optional[List[str]] = None) -> None:
        spec = job.setdefault("spec", {}).setdefault(
            "servingReplicaSpecs", {}
        ).setdefault(servingapi.REPLICA_REPLICA, {})
        spec["replicas"] = count
        ann = job.setdefault("metadata", {}).setdefault("annotations", {})
        if drain:
            ann[DRAIN_ANNOTATION] = json.dumps(sorted(drain))
        else:
            ann.pop(DRAIN_ANNOTATION, None)
        self.cluster.update(self.KIND, job)

    def _clear_drain_annotation(self, job: Dict[str, Any]) -> None:
        ann = (job.get("metadata") or {}).get("annotations") or {}
        if DRAIN_ANNOTATION not in ann:
            return
        ann.pop(DRAIN_ANNOTATION, None)
        job.setdefault("metadata", {})["annotations"] = ann
        self.cluster.update(self.KIND, job)

    def _record(self, job: Dict[str, Any], event: str,
                detail: Dict[str, Any]) -> None:
        if self.recorder is None:
            return
        md = job.get("metadata") or {}
        self.recorder.record(
            f"{objects.namespace_of(job)}/{md.get('name', '')}",
            "servefleet", event, detail, uid=md.get("uid"),
        )

    def _tick_job(self, job: Dict[str, Any]) -> None:
        md = job.get("metadata") or {}
        job_key = f"{objects.namespace_of(job)}/{md.get('name', '')}"
        auto = servingapi.AutoscaleSpec.from_dict(
            (job.get("spec") or {}).get("autoscale")
        )
        if self.reqrecorder is not None and self.reqrecorder.enabled:
            self.reqrecorder.set_slo(
                job_key,
                servingapi.SLOSpec.from_dict(
                    (job.get("spec") or {}).get("slo")
                ),
            )
        replicas = self._replicas_of(job)
        now = self.clock()
        with self._lock:
            tele = dict(self._telemetry.get(job_key, {}))
            p99 = self._queue_wait_p99(job_key, now)
            blocked = self._blocked_delta(job_key, tele)
        used = sum(
            t.total_blocks - t.free_blocks for t in tele.values()
        )
        total = sum(t.total_blocks for t in tele.values())
        # total == 0 means NO replica has reported block telemetry:
        # unknown, not idle — decide() vetoes scale-in on None
        occupancy = (used / total) if total else None
        self._publish_status(job_key, replicas, tele, occupancy or 0.0, p99)
        if auto is None or replicas is None:
            # autoscaling removed (or the spec lost its count): a drain
            # left mid-flight must be RELEASED, not parked forever — the
            # annotation would keep the victim fenced off dispatch while
            # nothing ever finishes the scale-in
            victim = self._draining.pop(job_key, None)
            if victim is not None:
                self._drain_since.pop(job_key, None)
                if replicas is not None:
                    self._patch_replicas(job, replicas, drain=None)
                else:
                    # no count to re-assert, but the annotation must
                    # still come off — a fenced victim with nothing ever
                    # finishing the scale-in serves nobody forever
                    self._clear_drain_annotation(job)
            return
        # ----- phase 2 of a scale-in: the victim finished draining?
        victim = self._draining.get(job_key)
        if victim is not None:
            timed_out = False
            if self.inflight_of is not None:
                # in-process router hook: live truth, wait it out
                inflight = self.inflight_of(job_key, victim)
            else:
                # telemetry path: a victim that died permanently
                # mid-drain never reports again — its last report's
                # inflight would wedge this job's autoscaling forever.
                # Stale/absent reports (or a drain older than the
                # timeout) complete the drain on the evidence available:
                # a dead replica has nothing in flight to protect, and a
                # hung one is bounded disruption vs a permanent wedge.
                t = tele.get(victim)
                inflight = t.inflight if t is not None else 0
                started = self._drain_since.setdefault(job_key, now)
                timed_out = (
                    t is None
                    or now - t.ts > self.drain_timeout_s
                    or now - started > self.drain_timeout_s
                )
            if inflight > 0 and not timed_out:
                return  # keep waiting; dispatch to it is already stopped
            target = max(replicas - 1, auto.min_replicas)
            self._drain_since.pop(job_key, None)
            del self._draining[job_key]
            if target >= replicas:
                # minReplicas was raised mid-drain at or past the
                # current count: the drain is ABANDONED — the victim is
                # released at the UNCHANGED count (growing the fleet is
                # the decide() path's job, and recording a
                # replica_drained / dir=in here would report a scale-in
                # that never happened)
                self._patch_replicas(job, replicas, drain=None)
                self._policy_for(job_key, auto).acted(now, "in")
                return
            self._patch_replicas(job, target, drain=None)
            # retire the deleted replica's telemetry: a ghost report must
            # not keep deflating fleet occupancy (or show as draining in
            # describe) after the pod is gone
            with self._lock:
                self._telemetry.get(job_key, {}).pop(victim, None)
                self._blocked_prev.get(job_key, {}).pop(victim, None)
                tele.pop(victim, None)
            self._publish_status(job_key, target, tele, occupancy, p99)
            metrics.SERVING_FLEET_SCALE_EVENTS.inc({"dir": "in"})
            detail = {"replica": victim, "replicas": target}
            if timed_out and inflight > 0:
                detail["timed_out"] = True
            self._record(job, "replica_drained", detail)
            self._note_scale(job_key, "in", victim, now)
            self._policy_for(job_key, auto).acted(now, "in")
            return
        decision = self._policy_for(job_key, auto).decide(
            now, replicas, p99, blocked, occupancy
        )
        if decision.direction == "out":
            target = min(replicas + 1, auto.max_replicas)
            self._patch_replicas(job, target)
            metrics.SERVING_FLEET_SCALE_EVENTS.inc({"dir": "out"})
            self._record(job, "scale_out",
                         {**decision.detail, "replicas": target})
            self._note_scale(job_key, "out", decision.trigger, now)
            self._policy_for(job_key, auto).acted(now, "out")
        elif decision.direction == "in":
            # phase 1: pick the victim the engine's scale-down delete
            # will take (highest index) and stop dispatch to it
            victim = self._victim_of(job, replicas)
            if victim is None:
                return
            self._draining[job_key] = victim
            self._drain_since[job_key] = now
            self._patch_replicas(job, replicas, drain=[victim])
            self._record(job, "scale_in",
                         {**decision.detail, "replica": victim})
            if self.on_drain is not None:
                self.on_drain(job_key, victim)

    def _policy_for(self, job_key: str,
                    auto: servingapi.AutoscaleSpec) -> AutoscalePolicy:
        policy = self._policies.get(job_key)
        if policy is None or policy.spec != auto:
            # a changed autoscale block gets fresh thresholds but keeps
            # the running cooldown — a spec edit must not grant a free
            # immediate scale action
            fresh = AutoscalePolicy(
                auto, out_cooldown_s=self.interval,
                in_cooldown_s=self.cooldown_s,
            )
            if policy is not None:
                fresh._cooldown_until = policy._cooldown_until
            self._policies[job_key] = fresh
            policy = fresh
        return policy

    def _victim_of(self, job: Dict[str, Any], replicas: int) -> Optional[str]:
        if replicas < 1:
            return None
        name = (job.get("metadata") or {}).get("name", "")
        rt = servingapi.REPLICA_REPLICA.lower()
        return f"{name}-{rt}-{replicas - 1}"

    def _note_scale(self, job_key: str, direction: str, what: str,
                    now: float) -> None:
        with _STATUS_LOCK:
            doc = _FLEET_STATUS.setdefault(job_key, {})
            doc["last_scale"] = {
                "dir": direction, "detail": what, "t": round(now, 3),
            }

    def _publish_status(
        self, job_key: str, replicas: Optional[int],
        tele: Dict[str, ReplicaTelemetry], occupancy: float, p99: float,
    ) -> None:
        with _STATUS_LOCK:
            doc = _FLEET_STATUS.setdefault(job_key, {})
            doc["replicas"] = replicas
            doc["occupancy"] = round(occupancy, 4)
            doc["queue_wait_p99_s"] = round(p99, 4)
            doc["per_replica"] = {
                rid: {
                    "free_blocks": t.free_blocks,
                    "total_blocks": t.total_blocks,
                    "queue_depth": t.queue_depth,
                    "inflight": t.inflight,
                }
                for rid, t in sorted(tele.items())
            }
            doc["draining"] = self._draining.get(job_key)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.tick()
