"""Prometheus metrics — job lifecycle counters labeled by namespace.

Reference parity (SURVEY.md §5.5): tf_operator_jobs_created_total
(job.go:30-37), _deleted_total (controller.go:70-77), _successful_total /
_failed_total (status.go:48-62), _restarted_total (pod.go:57-65),
tf_operator_is_leader gauge (server.go:64-69). Exposition is the Prometheus
text format, served by the CLI's metrics endpoint.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

_REGISTRY: List["Metric"] = []
_LOCK = threading.Lock()


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


class Metric:
    TYPE = "counter"

    def __init__(self, name: str, help_text: str) -> None:
        self.name = name
        self.help = help_text
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        with _LOCK:
            _REGISTRY.append(self)

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Snapshot of every label set's value (bench rows and tests that
        need the whole family, e.g. the per-verb/kind API-request tally)."""
        with _LOCK:
            return dict(self._values)

    @staticmethod
    def _escape_label_value(v: str) -> str:
        """Prometheus text-format label escaping: backslash, double quote,
        and line feed must be escaped or one bad value (e.g. a job name
        quoted inside an error-message label) corrupts the whole
        exposition."""
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    def _render_labels(self, key) -> str:
        if not key:
            return ""
        inner = ",".join(
            f'{k}="{self._escape_label_value(v)}"' for k, v in key
        )
        return "{" + inner + "}"

    def expose(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.TYPE}",
        ]
        with _LOCK:  # snapshot; inc/set mutate _values in place under _LOCK
            values = dict(self._values) or {(): 0.0}
        for key, v in sorted(values.items()):
            lines.append(f"{self.name}{self._render_labels(key)} {v:g}")
        return "\n".join(lines)

    def reset(self) -> None:
        with _LOCK:
            self._values.clear()


class Counter(Metric):
    TYPE = "counter"

    def inc(self, labels: Optional[Dict[str, str]] = None, amount: float = 1.0) -> None:
        with _LOCK:
            k = _label_key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with _LOCK:
            self._values[_label_key(labels)] = value

    def remove(self, labels: Optional[Dict[str, str]] = None) -> None:
        """Drop one label-set's series (e.g. a replica that left the
        fleet) — without this the gauge exports its last value forever
        and per-entity label cardinality only ever grows."""
        with _LOCK:
            self._values.pop(_label_key(labels), None)


class Histogram(Metric):
    """Prometheus histogram: cumulative le-buckets + _sum + _count.
    Default buckets suit controller reconcile latencies (sub-ms to 10s)."""

    TYPE = "histogram"
    DEFAULT_BUCKETS = (
        0.001, 0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.15, 0.25, 0.35, 0.5,
        0.75, 1.0, 2.5, 5.0, 10.0,
    )

    def __init__(self, name: str, help_text: str, buckets=None) -> None:
        super().__init__(name, help_text)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        # per label-set: [bucket counts..., +Inf count], sum
        self._obs: Dict[Tuple[Tuple[str, str], ...], list] = {}

    def observe(
        self, value: float, labels: Optional[Dict[str, str]] = None
    ) -> None:
        with _LOCK:
            k = _label_key(labels)
            if k not in self._obs:
                self._obs[k] = [[0] * (len(self.buckets) + 1), 0.0]
            counts, total = self._obs[k]
            for i, le in enumerate(self.buckets):
                if value <= le:
                    counts[i] += 1
            counts[-1] += 1  # +Inf
            self._obs[k][1] = total + value

    def count(self, labels: Optional[Dict[str, str]] = None) -> int:
        with _LOCK:
            obs = self._obs.get(_label_key(labels))
            return obs[0][-1] if obs else 0

    def percentiles(
        self, qs, labels: Optional[Dict[str, str]] = None
    ) -> Dict[float, Optional[float]]:
        """Approximate quantiles from the cumulative le-buckets: the upper
        bound of the first bucket whose count reaches the target rank
        (None when the quantile falls beyond the last finite bucket —
        prometheus histogram_quantile semantics, conservative upper
        bound).  The rank is ceil(q * total) clamped to >= 1 so it always
        names a WHOLE observation: q=0 asks for the smallest observation
        (rank 1), not "the first bucket whether or not anything landed in
        it" — the raw-rank form returned buckets[0] for q=0 even when
        that bucket was empty."""
        with _LOCK:
            obs = self._obs.get(_label_key(labels))
            counts = list(obs[0]) if obs else None
        if not counts or counts[-1] == 0:
            return {q: None for q in qs}
        total = counts[-1]
        out: Dict[float, Optional[float]] = {}
        for q in qs:
            rank = max(1, math.ceil(q * total))
            out[q] = next(
                (le for i, le in enumerate(self.buckets)
                 if counts[i] >= rank),
                None,
            )
        return out

    def expose(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.TYPE}",
        ]
        with _LOCK:  # snapshot: observe() mutates the counts lists in place
            snapshot = {k: (list(v[0]), v[1]) for k, v in self._obs.items()}
        for key, (counts, total) in sorted(snapshot.items()):
            base = dict(key)
            for i, le in enumerate(self.buckets):
                lk = self._render_labels(
                    _label_key({**base, "le": f"{le:g}"})
                )
                lines.append(f"{self.name}_bucket{lk} {counts[i]}")
            lk = self._render_labels(_label_key({**base, "le": "+Inf"}))
            lines.append(f"{self.name}_bucket{lk} {counts[-1]}")
            plain = self._render_labels(key)
            # full precision, not %g: a long-lived operator's sum must keep
            # advancing by sub-ms observations or rate() reads zero
            lines.append(f"{self.name}_sum{plain} {total!r}")
            lines.append(f"{self.name}_count{plain} {counts[-1]}")
        return "\n".join(lines)

    def reset(self) -> None:
        with _LOCK:
            self._obs.clear()
            self._values.clear()


def expose_all() -> str:
    # each expose() snapshots under _LOCK itself (non-reentrant lock — the
    # registry list is copied here so a concurrent Metric() init can't race
    # the iteration)
    with _LOCK:
        registry = list(_REGISTRY)
    return "\n".join(m.expose() for m in registry) + "\n"


def reset_all() -> None:
    with _LOCK:
        registry = list(_REGISTRY)
    for m in registry:
        m.reset()


PREFIX = "tpu_operator"

JOBS_CREATED = Counter(
    f"{PREFIX}_jobs_created_total", "Counts number of jobs created"
)
JOBS_DELETED = Counter(
    f"{PREFIX}_jobs_deleted_total", "Counts number of jobs deleted"
)
JOBS_SUCCEEDED = Counter(
    f"{PREFIX}_jobs_successful_total", "Counts number of jobs completed successfully"
)
JOBS_FAILED = Counter(
    f"{PREFIX}_jobs_failed_total", "Counts number of jobs failed"
)
JOBS_RESTARTED = Counter(
    f"{PREFIX}_jobs_restarted_total", "Counts number of jobs restarted"
)
IS_LEADER = Gauge(
    f"{PREFIX}_is_leader", "1 when this operator instance holds leadership"
)
RECONCILE_DURATION = Histogram(
    f"{PREFIX}_reconcile_duration_seconds",
    "Per-sync reconcile latency distribution "
    "(the reference only logs these durations — controller.go:303-307)",
)
SYNC_PHASE_DURATION = Histogram(
    f"{PREFIX}_sync_phase_duration_seconds",
    "Per-phase reconcile latency, fed by the span tracer "
    "(engine/tracing.py): where inside a sync the time went",
)
WORKQUEUE_DEPTH = Gauge(
    f"{PREFIX}_workqueue_depth",
    "Keys currently waiting in the per-kind reconcile work queue",
)
WORKQUEUE_LATENCY = Histogram(
    f"{PREFIX}_workqueue_latency_seconds",
    "Enqueue-to-sync latency: how long a key waited in the work queue "
    "before a worker picked it up",
)
SYNC_ERRORS = Counter(
    f"{PREFIX}_sync_errors_total",
    "Reconcile syncs that returned an error (requeued with backoff)",
)
RUNNING_REPLICAS = Gauge(
    f"{PREFIX}_running_replicas",
    "Pods currently Running, aggregated across jobs by kind and "
    "replica type",
)
CONTROL_OPS = Counter(
    f"{PREFIX}_control_operations_total",
    "Pod/Service create/delete operations issued by the control layer",
)
RESTART_BACKOFF = Histogram(
    f"{PREFIX}_restart_backoff_seconds",
    "Crash-loop backoff applied to ExitCode delete-for-recreate restarts "
    "(0 = free restart within the grace budget); one observation per "
    "restart, so _count tracks restarts and _sum the delay imposed",
    buckets=(0.0, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0),
)
API_RETRIES = Counter(
    f"{PREFIX}_api_request_retries_total",
    "ClusterClient requests replayed after a retryable apiserver error "
    "(429/5xx/connection reset), labeled by the error class",
)
WATCH_RESTARTS = Counter(
    f"{PREFIX}_watch_restarts_total",
    "Watch streams re-established after a drop, labeled by kind and "
    "reason (gone = 410 resourceVersion expiry forcing a relist, "
    "error = transport loss resuming from the last resourceVersion)",
)
SYNC_RETRIES_EXHAUSTED = Counter(
    f"{PREFIX}_sync_retries_exhausted_total",
    "Reconcile keys that burned the bounded retry budget on "
    "non-transient errors and fell back to the flat max-backoff cadence",
)
API_REQUESTS = Counter(
    f"{PREFIX}_api_requests_total",
    "Logical API-server requests issued through the operator's cluster "
    "client (FakeCluster or ClusterClient), labeled by verb "
    "(get/list/create/update/update_status/delete) and kind — the "
    "'zero steady-state LISTs per reconcile' claim is asserted on the "
    "{verb=list,kind=Pod|Service} series",
)
CACHED_LIST_HITS = Counter(
    f"{PREFIX}_cached_list_hits_total",
    "Dependent (pod/service) reads on the sync hot path served from the "
    "indexed informer cache instead of an API LIST, labeled by kind",
)
CACHED_LIST_MISSES = Counter(
    f"{PREFIX}_cached_list_misses_total",
    "Dependent reads that fell back to a live API LIST, labeled by kind "
    "and reason (no_lister = engine running without informer wiring, "
    "not_synced = informer cache not yet listed)",
)
TRANSPORT_CONNECTIONS_CREATED = Counter(
    f"{PREFIX}_transport_connections_created_total",
    "TCP/TLS connections dialed by the keep-alive HttpTransport (pool "
    "misses plus one dedicated connection per watch stream); in steady "
    "state this stays near the pool size while reuse tracks request "
    "volume",
)
TRANSPORT_CONNECTIONS_REUSED = Counter(
    f"{PREFIX}_transport_connections_reused_total",
    "Requests served on a pooled keep-alive connection instead of a "
    "fresh handshake — created vs reused is the pool's hit ratio",
)
CONTROL_FANOUT_BATCH = Histogram(
    f"{PREFIX}_control_fanout_batch_ops",
    "Operations dispatched per slow-start control fan-out batch "
    "(client-go slowStartBatch: 1, 2, 4, ... capped by --control-fanout; "
    "a distribution stuck at 1 means serial mode or constant early "
    "failures)",
    buckets=(1, 2, 4, 8, 16, 32, 64),
)
CONTROL_OP_DURATION = Histogram(
    f"{PREFIX}_control_op_duration_seconds",
    "Latency of one pod/service create/delete issued by the control "
    "layer, labeled by kind and verb — the per-operation cost the "
    "transport pool and control fan-out exist to hide",
)

# ------------------------------------------------------- sharded control plane
# Shard ownership, failover, and fencing (engine/sharding.py + the
# ShardedOperator in cmd/manager.py), plus the APF-style admission layer in
# e2e/http_apiserver.py — the ISSUE 6 families.
SHARD_JOBS_OWNED = Gauge(
    f"{PREFIX}_shard_jobs_owned",
    "Jobs currently owned by each shard (rendezvous slot ownership held "
    "via per-slot Leases), labeled by shard and kind; the sum across "
    "shards tracks total jobs and a skewed distribution means a hot "
    "shard",
)
SHARD_SLOTS_OWNED = Gauge(
    f"{PREFIX}_shard_slots_owned",
    "Shard slots whose Lease each shard currently holds; in steady state "
    "1 per live shard, >1 on a survivor that absorbed a crashed peer's "
    "slot",
)
SHARD_FAILOVERS = Counter(
    f"{PREFIX}_shard_failovers_total",
    "Slot ownership transfers after a lease lapse (crash failover or "
    "shrink takeover), labeled by the slot and the new owning shard — "
    "every increment is a re-list + re-adopt of that slot's jobs",
)
FENCING_REJECTIONS = Counter(
    f"{PREFIX}_fencing_rejections_total",
    "Status writes rejected by the store because their fencing token's "
    "lease generation was stale — a zombie shard trying to write after "
    "losing its slot; any nonzero rate means a failover raced a "
    "still-running old owner (and the barrier held)",
)
APF_QUEUE_DEPTH = Gauge(
    f"{PREFIX}_apf_queue_depth",
    "Requests currently parked in each tenant flow's admission queue "
    "(APF-style priority-and-fairness layer in the e2e http apiserver), "
    "labeled by flow",
)
APF_DISPATCHED = Counter(
    f"{PREFIX}_apf_dispatched_total",
    "Requests admitted to execution by the fair-share dispatcher, "
    "labeled by flow — compare across flows to see fairness in action",
)
APF_REJECTED = Counter(
    f"{PREFIX}_apf_rejected_total",
    "Requests rejected with 429+Retry-After because the flow's queue was "
    "full or the queue wait timed out, labeled by flow and reason "
    "(queue_full | timeout); a noisy tenant shows up here while other "
    "flows stay clean",
)
APF_QUEUE_WAIT = Histogram(
    f"{PREFIX}_apf_queue_wait_seconds",
    "How long an admitted request waited in its flow queue before a seat "
    "freed up, labeled by flow — the fairness SLO: a noisy tenant must "
    "not drag other flows' p99",
)
APF_SEATS_IN_USE = Gauge(
    f"{PREFIX}_apf_seats_in_use",
    "Execution seats each flow currently occupies, labeled by flow; with "
    "a per-flow seat cap configured this saturating at the cap while "
    "other flows keep dispatching is the isolation working — one "
    "crash-looping client cannot occupy every seat",
)

# -------------------------------------------------- multi-process plane
# The multi-process control plane (cmd/supervisor.py + the write-ahead
# watch journal in e2e/apiserver.py): worker-process lifecycle and the
# apiserver-side cost of serving N independent process watchers.
SUPERVISOR_RESTARTS = Counter(
    f"{PREFIX}_supervisor_restarts_total",
    "Worker processes the shard supervisor observed dead and scheduled "
    "for restart, labeled by shard; every restart is a NEW fencing "
    "identity, so the dead incarnation's in-flight writes stay fenced",
)
SUPERVISOR_CHILDREN = Gauge(
    f"{PREFIX}_supervisor_children",
    "Shard worker processes by state (running | down); down > 0 for "
    "longer than the restart backoff means a crash loop",
)
WATCH_JOURNAL_EVENTS = Counter(
    f"{PREFIX}_watch_journal_events_total",
    "Events appended to the apiserver's bounded write-ahead watch "
    "journal, labeled by kind; the journal is what lets each watcher "
    "process resume from its own resourceVersion cursor instead of "
    "re-listing the world",
)
WATCH_JOURNAL_RESUMES = Counter(
    f"{PREFIX}_watch_journal_resumes_total",
    "Watch streams opened with a resourceVersion cursor, labeled by kind "
    "and outcome: hit = the journal still covered the cursor and the "
    "stream resumed from it; miss = the cursor had fallen behind the "
    "journal's horizon and the watcher was sent 410 Gone to relist — "
    "hit/(hit+miss) is the journal hit ratio the bench rows record",
)
WATCH_JOURNAL_ENCODES = Counter(
    f"{PREFIX}_watch_journal_encodes_total",
    "Watch events serialized for the wire, labeled by kind and source: "
    "encode = JSON built for the first watcher to need the entry, cache "
    "= a later watcher reused the journal's stored bytes; with N worker "
    "processes watching, cache/(cache+encode) approaches (N-1)/N",
)

# ------------------------------------------------------------- warm pools
# Warm-pool pod placement (engine/warmpool.py): pre-provisioned standby
# pods per slice shape that job pod creation claims instead of paying the
# image-pull + init cold start.
WARM_POOL_SIZE = Gauge(
    f"{PREFIX}_warm_pool_size",
    "Unclaimed standby pods per slice shape, labeled by shape and state: "
    "ready (Running, claimable) vs filling (created, still paying pull/"
    "init latency); ready should sit at the configured K in steady state",
)
WARM_POOL_CLAIMS = Counter(
    f"{PREFIX}_warm_pool_claims_total",
    "Job replica creations served by claiming a ready warm pod (the CAS "
    "relabel) instead of a cold create, labeled by shape — "
    "claims / (claims + cold creates) is the warm-hit ratio",
)
WARM_POOL_CLAIM_MISSES = Counter(
    f"{PREFIX}_warm_pool_claim_misses_total",
    "Claim attempts that fell back toward a cold create, labeled by shape "
    "and reason: empty (no ready standby), contested (lost the CAS to a "
    "rival claimer), image_mismatch (strict image matching enabled and no "
    "pre-pulled match), namespace (pool serves a different namespace)",
)
WARM_POOL_REPLENISH = Counter(
    f"{PREFIX}_warm_pool_replenish_total",
    "Standby pods created by the asynchronous pool refill (slow-start "
    "fan-out, retry ladder under apiserver errors), labeled by shape; "
    "rate tracks the claim rate in steady state",
)
# ------------------------------------------------------------- scheduler
# Cluster scheduler (engine/scheduler.py): gang admission, bin-packing,
# preemption over the simulated Node inventory — the ISSUE 8 families.
SCHEDULER_PENDING_GANGS = Gauge(
    f"{PREFIX}_scheduler_pending_gangs",
    "Gangs currently waiting for capacity (admission failed, Scheduling "
    "condition stamped on the job); a persistently nonzero value means "
    "the cluster is oversubscribed or fragmented",
)
SCHEDULER_BINDS = Counter(
    f"{PREFIX}_scheduler_binds_total",
    "Gangs admitted: the whole member set atomically reserved node "
    "capacity, labeled by the scoring policy that placed it",
)
SCHEDULER_PREEMPTIONS = Counter(
    f"{PREFIX}_scheduler_preemptions_total",
    "Lower-priority gangs evicted (SIGTERM/143, reservation released, "
    "gang requeued) to admit a higher-priority arrival, labeled by "
    "policy",
)
SCHEDULER_BIND_LATENCY = Histogram(
    f"{PREFIX}_scheduler_bind_latency_seconds",
    "Gang admission wait: first failed admission to successful bind "
    "(0 for gangs admitted on first attempt), labeled by policy — the "
    "queueing delay capacity pressure imposes",
    buckets=(0.0, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
             1800.0),
)
SCHEDULER_SHRINKS = Counter(
    f"{PREFIX}_scheduler_shrinks_total",
    "Elastic victims resized down to their kubeflow.org/min-replicas "
    "floor (spec patched; the victim's own drain -> checkpoint -> "
    "resume transition executes the shrink) to admit a higher-priority "
    "arrival instead of evicting the whole gang, labeled by policy",
)
SCHEDULER_FRAGMENTATION = Gauge(
    f"{PREFIX}_scheduler_fragmentation_ratio",
    "1 - (largest contiguous free block / total free chips) over the "
    "Node inventory: 0 = all free capacity in one slice (a big gang can "
    "land), toward 1 = free chips are crumbs no large slice fits in; "
    "`packed` exists to keep this low",
)

# ------------------------------------------------------------- flight recorder
# Per-job SLO families derived by the job flight recorder
# (engine/timeline.py) from milestone records — ground truth per job
# (first bind, first Running condition, failure-to-Running repair), not
# inference from aggregate counters.
_SLO_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0,
    600.0, 1800.0, 3600.0,
)
JOB_TIME_TO_SCHEDULED = Histogram(
    f"{PREFIX}_job_time_to_scheduled_seconds",
    "Per-job time from first timeline contact (creation) to placement: "
    "the cluster scheduler's gang bind, or the first pod create / warm "
    "claim when no scheduler runs — the queueing SLO policy schedulers "
    "are judged on",
    buckets=_SLO_BUCKETS,
)
JOB_TIME_TO_RUNNING = Histogram(
    f"{PREFIX}_job_time_to_running_seconds",
    "Per-job time from creation to the first Running condition — the "
    "end-to-end startup SLO (admission + placement + image pull + "
    "runtime init), observed once per job from its timeline",
    buckets=_SLO_BUCKETS,
)
JOB_RESTART_MTTR = Histogram(
    f"{PREFIX}_job_restart_mttr_seconds",
    "Per-incident repair time: earliest failure evidence in the job's "
    "timeline (injected kill, preemption, Restarting condition) to the "
    "next Running condition — mean time to recovery from ground truth",
    buckets=_SLO_BUCKETS,
)
JOB_RESIZE_DURATION = Histogram(
    f"{PREFIX}_job_resize_duration_seconds",
    "Per-resize elastic transition time: resize_requested to resumed in "
    "the job's timeline (drain + checkpoint reshard + recreate + "
    "re-warmup to all-replicas-Running) — the SLO a failure-atomic "
    "resize is judged on; reverted transitions are not observed",
    buckets=_SLO_BUCKETS,
)
JOB_TIMELINE_EVENTS = Counter(
    f"{PREFIX}_job_timeline_events_total",
    "Records appended to per-job flight-recorder timelines, labeled by "
    "source subsystem (informer/workqueue/sync/controller/scheduler/"
    "warmpool/fanout/fencing/chaos/shard) — the recorder's own write "
    "volume",
)
JOB_TIMELINE_EVICTIONS = Counter(
    f"{PREFIX}_job_timeline_evictions_total",
    "Finished-job timelines evicted by the recorder's LRU when the "
    "tracked-job cap was hit; live jobs are never evicted, so a high "
    "rate just means --timeline-max-jobs is small relative to job churn",
)

CREATE_TO_RUNNING = Histogram(
    f"{PREFIX}_create_to_running_seconds",
    "Replica-needed to replica-Running latency, labeled by path: cold "
    "(fresh create paying image pull + runtime init), warm (claimed from "
    "the warm pool — the latency the pool exists to delete), pool_fill "
    "(a standby pod paying the cold start off the job critical path)",
    buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0,
             180.0, 300.0, 600.0),
)


class ReplicaGaugeTracker:
    """Aggregates per-job active-replica counts into a {kind,replica_type}
    gauge. A single job's reconcile only knows its own counts, so the
    tracker keeps the per-job breakdown and re-sums on every update;
    `forget()` (job finished/deleted) removes the job's contribution."""

    def __init__(self, gauge: Gauge) -> None:
        self._gauge = gauge
        # (kind, replica_type) -> {job_key: active_count}
        self._counts: Dict[Tuple[str, str], Dict[str, int]] = {}
        self._tracker_lock = threading.Lock()

    # gauge.set runs INSIDE _tracker_lock: setting outside would let a
    # concurrent forget()/update() pair publish sums out of order and
    # leave a stale value until the next reconcile touches the type
    # (safe: nothing acquires _tracker_lock while holding the metrics
    # lock, so the ordering is acyclic)
    def update(self, kind: str, job_key: str, active: Dict[str, int]) -> None:
        with self._tracker_lock:
            touched = set()
            for rtype, count in active.items():
                self._counts.setdefault((kind, rtype), {})[job_key] = count
                touched.add((kind, rtype))
            # replica types this job no longer declares drop to zero
            for (k, rtype), per_job in self._counts.items():
                if k == kind and rtype not in active and job_key in per_job:
                    del per_job[job_key]
                    touched.add((k, rtype))
            for (k, rtype) in touched:
                self._gauge.set(
                    sum(self._counts[(k, rtype)].values()),
                    {"kind": k, "replica_type": rtype},
                )

    def forget(self, kind: str, job_key: str) -> None:
        with self._tracker_lock:
            for (k, rtype), per_job in self._counts.items():
                if k == kind and per_job.pop(job_key, None) is not None:
                    self._gauge.set(
                        sum(per_job.values()),
                        {"kind": k, "replica_type": rtype},
                    )

    def reset(self) -> None:
        with self._tracker_lock:
            self._counts.clear()
        self._gauge.reset()


RUNNING_REPLICAS_TRACKER = ReplicaGaugeTracker(RUNNING_REPLICAS)


# --------------------------------------------------------------- serving
# Serving-path families (models/telemetry.py feeds them from serve_loop;
# models/speculative.py feeds the draft counters from speculative_generate
# with path="speculative_generate").  Same registry, same exposition
# endpoint as the operator families — one scrape covers both halves.
#
# Sub-ms buckets: a CPU smoke lane emits tokens in tens of microseconds
# and a TPU decode step lands around 5-20ms — the reconcile-tuned default
# buckets would collapse TPOT into its first bucket.
_SERVING_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

SERVING_TTFT = Histogram(
    f"{PREFIX}_serving_ttft_seconds",
    "Time to first token: lane admission to the request's first sampled "
    "token (queue wait excluded — that is its own histogram)",
    buckets=_SERVING_LATENCY_BUCKETS,
)
SERVING_TPOT = Histogram(
    f"{PREFIX}_serving_tpot_seconds",
    "Time per output token: a finished request's decode wall-clock over "
    "its decoded tokens (first token excluded), one observation per "
    "request with >= 2 tokens",
    buckets=_SERVING_LATENCY_BUCKETS,
)
SERVING_QUEUE_WAIT = Histogram(
    f"{PREFIX}_serving_queue_wait_seconds",
    "How long a request sat queued before a decode lane was reserved "
    "for it",
    buckets=_SERVING_LATENCY_BUCKETS,
)
SERVING_REQUEST_LATENCY = Histogram(
    f"{PREFIX}_serving_request_latency_seconds",
    "End-to-end request latency: enqueue to final token (queue wait + "
    "prefill + decode)",
    buckets=_SERVING_LATENCY_BUCKETS,
)
SERVING_REQUESTS = Counter(
    f"{PREFIX}_serving_requests_total",
    "Requests finished by the serving loop",
)
SERVING_TOKENS = Counter(
    f"{PREFIX}_serving_tokens_total",
    "Tokens emitted to finished requests (EOS included when hit)",
)
SERVING_PREFILL_TIME = Counter(
    f"{PREFIX}_serving_prefill_seconds_total",
    "Wall-clock spent prefilling prompts into lane caches (the other "
    "half of the prefill-vs-decode split)",
)
SERVING_DECODE_TIME = Counter(
    f"{PREFIX}_serving_decode_seconds_total",
    "Wall-clock spent in decode blocks (device step + token readback)",
)
SERVING_BATCH_OCCUPANCY = Gauge(
    f"{PREFIX}_serving_batch_occupancy",
    "Decode lanes occupied by live requests, sampled at each decode "
    "block (bounded by the serve loop's slots)",
)
SERVING_ACCEPTED_DRAFTS = Counter(
    f"{PREFIX}_serving_accepted_drafts_total",
    "Speculative draft tokens accepted by target verification "
    "(accepted/proposed is the acceptance rate); labeled by path: "
    "serve_loop or speculative_generate",
)
SERVING_PROPOSED_DRAFTS = Counter(
    f"{PREFIX}_serving_proposed_drafts_total",
    "Speculative draft tokens proposed to target verification; labeled "
    "by path: serve_loop or speculative_generate",
)
SERVING_HBM_PEAK = Gauge(
    f"{PREFIX}_serving_hbm_peak_bytes",
    "Per-device HBM high watermark sampled at the end of a serve_loop "
    "run (runtime/profiler.device_memory_stats); on backends without "
    "memory stats (CPU) no device-labeled sample is ever set and the "
    "family exposes only the default unlabeled 0",
)
# Paged-KV families (serve_loop paged=True; models/paging.py).  The
# *_kv_blocks_total gauge is a CAPACITY (how many blocks the pool was
# built with — a level, not a running count; the metrics lint carves
# out this one name from its gauges-must-not-end-_total rule), so
# used/total is the block-occupancy ratio the router/autoscaler scales
# on — the real memory signal, where lane occupancy saturates at
# `slots` long before HBM does.
SERVING_KV_BLOCKS_TOTAL = Gauge(
    f"{PREFIX}_serving_kv_blocks_total",
    "KV block-pool capacity (usable blocks; scratch excluded) of the "
    "serving process's paged cache — a capacity level, set at serve "
    "start; 0 means dense (unpaged) serving",
)
SERVING_KV_BLOCKS_USED = Gauge(
    f"{PREFIX}_serving_kv_blocks_used",
    "KV blocks currently allocated to live lanes and shared prefixes, "
    "sampled at every decode block — used/total is the block-level "
    "occupancy the autoscaler should scale on (lane occupancy "
    "saturates at `slots` long before memory does)",
)
SERVING_KV_BLOCK_COW_COPIES = Counter(
    f"{PREFIX}_serving_kv_block_cow_copies_total",
    "Copy-on-write block copies at admission: a shared prefix whose "
    "length is not a block multiple copies exactly its boundary block "
    "per lane (one block, not the dense path's whole-cache copy)",
)
SERVING_PREFIX_BLOCK_HITS = Counter(
    f"{PREFIX}_serving_prefix_block_hits_total",
    "Shared-prefix blocks reused by reference at admission instead of "
    "being re-prefilled or copied — each hit is one block of KV the "
    "admission did not have to produce",
)
SERVING_ADMISSION_BLOCKED = Counter(
    f"{PREFIX}_serving_admission_blocked_on_memory_total",
    "Admissions deferred by the memory gate: a decode lane was free and "
    "a request was queued, but the block pool could not cover the "
    "request's worst case — the request waits instead of OOMing "
    "(sampled once per serve-loop iteration while blocked)",
)
SERVING_PAGED_KERNEL_REQUESTS = Counter(
    f"{PREFIX}_serving_paged_kernel_requests_total",
    "Paged requests finished, labeled by the read path that served "
    "them (kernel=pallas: the block-indexed paged-attention kernel, "
    "models/paged_attention.py; kernel=gather: the table-gathered "
    "linear-view oracle) — the pallas/gather ratio is the "
    "fast-path-adoption signal after a rollout",
)
# Serving-fleet control plane (ISSUE 14): the occupancy-aware router
# (models/router.py) and the telemetry-driven fleet autoscaler
# (engine/servefleet.py).  The dispatch-reason breakdown is the router's
# health signal (occupancy vs queued vs redispatch), the replicas-by-
# state gauge is the fleet's shape, and scale-events-by-direction is the
# autoscaler's activity — docs/monitoring.md carries the PromQL.
SERVING_FLEET_REPLICAS = Gauge(
    f"{PREFIX}_serving_fleet_replicas",
    "Serving-fleet replicas by state (starting: claimed/created but not "
    "yet serving; ready: dispatchable; draining: finishing in-flight "
    "requests before scale-in; unhealthy: heartbeat stale, dispatch "
    "suspended) — set by the router/autoscaler from live telemetry",
)
SERVING_ROUTER_DISPATCH = Counter(
    f"{PREFIX}_serving_router_dispatch_total",
    "Router dispatch decisions by reason (occupancy: picked the replica "
    "with the most free KV blocks and shortest queue; round_robin: "
    "baseline policy; redispatch: re-routed exactly once off a dead "
    "replica; queued: no replica had capacity, request parked in the "
    "router queue; rejected: worst-case KV cost exceeds every known "
    "replica's whole pool — refused upfront instead of wedging the "
    "queue head)",
)
SERVING_ROUTER_QUEUE_DEPTH = Gauge(
    f"{PREFIX}_serving_router_queue_depth",
    "Requests parked in the router's queue because no healthy replica "
    "had free capacity (bounded per-replica in-flight admission) — "
    "sustained depth is the scale-out pressure signal",
)
SERVING_FLEET_SCALE_EVENTS = Counter(
    f"{PREFIX}_serving_fleet_scale_events_total",
    "Fleet autoscaler actions by direction (dir=out: replica added on a "
    "queue-wait/blocked-admission trigger; dir=in: replica drained and "
    "removed on the occupancy floor) — each event also lands as a "
    "DECISIONS record on the owning TPUServingJob's timeline",
)
# Serving-fleet failure domain (ISSUE 15): the scrape transport's
# health (attempts by outcome, per-replica age), and the router's
# degraded/ejection/hedging activity.  Scrape age is THE staleness
# signal the router's health expiry and degraded fallback key on;
# docs/monitoring.md carries the scrape-success-ratio, ejection-rate,
# and hedge-win-rate PromQL.
SERVING_SCRAPE_ATTEMPTS = Counter(
    f"{PREFIX}_serving_scrape_attempts_total",
    "Per-replica /metrics scrape attempts by outcome (ok; timeout: no "
    "response within --serving-scrape-timeout; http_error: non-200 "
    "status; truncated: a 200 whose exposition is missing the serving "
    "block families — half an exposition is no exposition; error: "
    "transport-level failure) — ok/total is the scrape success ratio",
)
SERVING_SCRAPE_AGE = Gauge(
    f"{PREFIX}_serving_scrape_age_seconds",
    "Seconds since each replica's last SUCCESSFUL scrape (labeled by "
    "serving_job and replica; not `job`, which Prometheus reserves for "
    "the scrape-target label and would rewrite to exported_job) — the staleness signal behind the router's health expiry "
    "and fleet-wide degraded fallback; a rising age on every replica "
    "at once means the scrape plane, not the fleet, is down",
)
SERVING_REPLICA_EJECTIONS = Counter(
    f"{PREFIX}_serving_replica_ejections_total",
    "Replicas ejected from dispatch after consecutive scrape or "
    "dispatch failures (models/router.py) — re-admission is half-open: "
    "a fresh telemetry sample after a capped-exponential backoff; each "
    "ejection re-dispatches the replica's unfinished requests exactly "
    "once and lands as a replica_ejected DECISION on the timeline",
)
SERVING_ROUTER_DEGRADED = Counter(
    f"{PREFIX}_serving_router_degraded_total",
    "Times the router entered DEGRADED mode: every replica's telemetry "
    "stale at once (the monitoring plane down, not the fleet), dispatch "
    "falls back to round-robin over READY replicas instead of parking "
    "the FIFO on blindness; recovery is the first fresh sample",
)
SERVING_HEDGE_REQUESTS = Counter(
    f"{PREFIX}_serving_hedge_requests_total",
    "Hedged (speculatively re-dispatched) requests by outcome: issued "
    "(first token overdue past the ceil-rank-p99 TTFT threshold, "
    "floor-clamped — a copy went to a sibling), won (the hedge copy "
    "carried the request: it delivered first, OR the original holder "
    "died/failed and the hedge copy was left to deliver), lost (the "
    "original carried it; the loser's completion is dropped by the "
    "dedup ledger) — won/issued is the hedge win rate that justifies "
    "the speculation budget; every race settles exactly once, at "
    "delivery or at a holder's death",
)
SERVING_KV_WINDOW_EVICTED = Counter(
    f"{PREFIX}_serving_kv_window_evicted_blocks_total",
    "KV block epochs retired by sliding-window rotation: a windowed "
    "lane's modular table wrapped past a block's positions — private "
    "blocks are reused in place, shared prefix blocks are dereferenced "
    "(and copied only while still partially visible); compare with "
    "the CoW-copy rate to see window pressure vs prefix-boundary cost",
)
# Iteration-level scheduling (ISSUE 19): the continuous scheduler's
# step-mix families — what one device dispatch actually carried, and
# the post-finish lane-steps both schedulers discard
SERVING_STEP_DECODE_ROWS = Gauge(
    f"{PREFIX}_serving_step_decode_rows",
    "Decode lanes advanced by the most recent serving dispatch (the "
    "ragged step's decode side; 0 between runs) — under the continuous "
    "scheduler this is the iteration batch the admission gate filled, "
    "under the slot loop it equals the block's busy-lane count",
)
SERVING_STEP_PREFILL_TOKENS = Gauge(
    f"{PREFIX}_serving_step_prefill_tokens",
    "Prefill tokens fused into the most recent serving dispatch beside "
    "its decode rows (continuous scheduler, paged mode: one admitted "
    "prompt's segment rides the same device step; 0 for slot-loop and "
    "unfused dispatches) — the fused-prefill ratio vs "
    "serving_step_decode_rows shows how much prefill the fleet hides "
    "inside decode steps",
)
SERVING_LANE_WASTED_STEPS = Counter(
    f"{PREFIX}_serving_lane_wasted_steps_total",
    "Lane-steps computed for already-finished lanes: the slot loop "
    "runs every lane to the steps_per_sync block edge and discards the "
    "post-EOS tail; the continuous scheduler freezes lanes on-device "
    "mid-block, leaving only the freeze-to-edge residue — a shrinking "
    "rate here is the iteration scheduler paying off",
)
# Disaggregated prefill/decode serving (ISSUE 20): the KV-block
# handoff between the prefill fleet and the decode fleet — the block
# table is the wire format.  phase= labels count blocks by what the
# wire carried: exported (payload bytes shipped) / elided (referenced
# by hash, bytes already at the receiver) on the send side, adopted
# (freshly allocated+written) / deduped (content-hash hit, incref
# only) on the receive side.  elided+deduped rates are the shared-
# prefix dedup actually saving wire and pool.
SERVING_HANDOFF_BLOCKS = Counter(
    f"{PREFIX}_serving_handoff_blocks_total",
    "KV blocks crossing the prefill→decode handoff by phase: "
    "exported/elided count the sender's wire composition (elided = "
    "shared-prefix blocks referenced by content hash, shipped "
    "earlier), adopted/deduped count the receiver's pool composition "
    "(deduped = hash hit, an incref instead of an alloc+write) — "
    "elided/exported and deduped/adopted are the hot-prefix transfer "
    "savings",
)
SERVING_HANDOFF_DURATION = Histogram(
    f"{PREFIX}_serving_handoff_duration_seconds",
    "Wall-clock of one lane's KV handoff half, by side: export "
    "(device_get + hashing + wire form on the prefill replica) and "
    "adopt (alloc + one jitted scatter on the decode replica) — the "
    "handoff's latency contribution to disaggregated TTFT; compare "
    "p99 against serving_ttft_seconds to see whether the wire or the "
    "compute dominates the split's overhead",
    buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
             1.0, 2.5),
)
SERVING_HANDOFF_RETRIES = Counter(
    f"{PREFIX}_serving_handoff_retries_total",
    "Handoffs bounced by decode-side admission (pool could not cover "
    "the export's fresh blocks plus decode growth) and re-placed on "
    "another decode replica by the router — a sustained rate is the "
    "decode fleet's KV capacity signal saturating; pair with "
    "serving_kv_blocks_used over the decode fleet before scaling",
)
# Request flight recorder + windowed SLO engine (ISSUE 16,
# engine/reqtrace.py): per-request causal timelines on the serving
# plane, and multi-window burn rates of the latency axes (TTFT / TPOT /
# queue-wait / e2e) against each TPUServingJob's spec.slo targets.
# docs/monitoring.md carries the burn-rate PromQL.
SERVING_SLO_BURN_RATE = Gauge(
    f"{PREFIX}_serving_slo_burn_rate",
    "Current SLO burn rate per latency axis (ttft/tpot/queue_wait/e2e) "
    "and evaluation window (fast/slow): bad-sample fraction divided by "
    "the error budget (1 - objective) — 1.0 burns the budget exactly at "
    "the allowed rate; a page fires when BOTH windows exceed the "
    "configured threshold (multi-window, so a single slow request "
    "cannot page and a sustained regression cannot hide)",
)
SERVING_SLO_WINDOW_P99 = Gauge(
    f"{PREFIX}_serving_slo_window_p99_seconds",
    "Sliding-window ceil-rank p99 of each latency axis (censored: a "
    "dropped request contributes +inf, so the gauge is only exported "
    "while the p99 is finite — an absent series under drops IS the "
    "signal, not a healthy zero)",
)
SERVING_SLO_BURNS = Counter(
    f"{PREFIX}_serving_slo_burns_total",
    "slo_burn DECISIONs emitted per latency axis: both burn-rate "
    "windows crossed the threshold, a record landed on the owning "
    "TPUServingJob's timeline and on the offending requests' — the "
    "page-worthy event count, rate-limited per axis by half the fast "
    "window",
)
SERVING_REQUEST_TIMELINE_EVENTS = Counter(
    f"{PREFIX}_serving_request_timeline_events_total",
    "Records appended to per-request flight-recorder timelines, labeled "
    "by source plane (router/replica/serving/slo) — the request "
    "recorder's own write volume",
)
SERVING_REQUEST_TIMELINE_EVICTIONS = Counter(
    f"{PREFIX}_serving_request_timeline_evictions_total",
    "Finished-request timelines evicted by the request recorder's LRU "
    "when the tracked-request cap was hit; in-flight requests are never "
    "evicted, so a high rate just means --reqtrace-max-requests is "
    "small relative to request churn",
)
