"""Controller expectations — double-creation protection under informer lag.

The equivalent of kubeflow/common pkg/controller.v1/expectation
(ControllerExpectations; usage at reference pod.go:176-180,
reconciler.go:23-35). A controller records how many creations/deletions it
has issued but not yet observed; while expectations are unsatisfied the sync
is skipped, so slow watch events can't cause duplicate pods (SURVEY.md §7.4
hard part 2).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

EXPECTATION_TTL_SECONDS = 5 * 60  # same 5-minute expiry as client-go


def gen_expectation_pods_key(job_key: str, replica_type: str) -> str:
    return f"{job_key}/{replica_type.lower()}/pods"


def gen_expectation_services_key(job_key: str, replica_type: str) -> str:
    return f"{job_key}/{replica_type.lower()}/services"


@dataclass
class _Expectation:
    add: int = 0
    delete: int = 0
    timestamp: float = field(default_factory=time.time)

    def fulfilled(self) -> bool:
        return self.add <= 0 and self.delete <= 0

    def expired(self, now: float) -> bool:
        return now - self.timestamp > EXPECTATION_TTL_SECONDS


class ControllerExpectations:
    def __init__(self, clock=time.time) -> None:
        self._lock = threading.Lock()
        self._store: Dict[str, _Expectation] = {}
        self._clock = clock

    def set_expectations(self, key: str, add: int, delete: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(add=add, delete=delete, timestamp=self._clock())

    def expect_creations(self, key: str, adds: int) -> None:
        self.set_expectations(key, adds, 0)

    def expect_deletions(self, key: str, dels: int) -> None:
        self.set_expectations(key, 0, dels)

    def raise_expectations(self, key: str, add: int, delete: int) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                self._store[key] = _Expectation(add=add, delete=delete, timestamp=self._clock())
            else:
                exp.add += add
                exp.delete += delete

    def lower_expectations(self, key: str, add: int, delete: int) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is not None:
                exp.add -= add
                exp.delete -= delete

    def creation_observed(self, key: str) -> None:
        self.lower_expectations(key, 1, 0)

    def deletion_observed(self, key: str) -> None:
        self.lower_expectations(key, 0, 1)

    def satisfied_expectations(self, key: str) -> bool:
        """True if fulfilled, expired, or never set (first sync must proceed)."""
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return True
            return exp.fulfilled() or exp.expired(self._clock())

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)
