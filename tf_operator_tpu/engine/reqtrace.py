"""Request flight recorder — one causal, bounded timeline per request.

PR 10 gave every *job* a flight recorder; the serving plane has since
grown into a distributed system (occupancy router, fleet autoscaler,
scrape transport, ejection, hedged re-dispatch) whose individual
*requests* have no story: when TTFT p99 blows up, nothing explains
whether the request queued at the router, lost a hedge race, rode a
degraded round-robin, or parked behind a paged-pool memory gate.  This
module is the per-request join: every plane appends structured,
monotonically-sequenced records keyed by (job, request id), so one
request's whole life — submit, router queue, dispatch, hedge race,
replica admission, prefill chunks, first token, finish (or ejection
re-dispatch, rejection, drop) — reads as a single ordered story, with
a hedged request's two arms as sibling ATTEMPTS under one timeline.

The design mirrors the job recorder (engine/timeline.py) exactly,
because its constraints are the same and proven:

  - **Bounded**: per request, one ring (``deque(maxlen=...)``) for
    routine progress (queued / dispatched / admitted / prefill_chunk /
    first_token / progress) and one for DECISIONS (hedge_issued / won /
    lost, redispatch, dispatch_failed, degraded entry/exit, memory-gate
    block, rejection, drop, slo_burn) — merged by sequence on read.  A
    long decode churns hundreds of progress records, and a single
    shared ring would evict the one hedge_lost record that explains the
    tail latency.  At most ``max_requests`` requests are tracked; past
    the cap the least-recently-touched FINISHED request is evicted
    (in-flight requests never are).
  - **Cheap on the hot path**: append is O(1) under the REQUEST's ring
    lock; the directory lock is taken only on first contact and on
    eviction.  ``progress`` records are additionally rate-limited per
    (request, replica) — the fleet simulator's per-step token scan must
    not flood the routine ring into amnesia.
  - **Causal**: records carry a per-request monotonic ``seq`` assigned
    under the ring lock; each ``dispatched`` record opens a new
    ATTEMPT, and later records are attributed to the attempt that owns
    their replica — the losing arm of a hedge race stays readable as
    "attempt 1 was dispatched, raced, and lost".
  - **Derived SLOs**: finish-time milestones feed a windowed SLO
    engine: sliding-window TTFT / TPOT / queue-wait / e2e samples
    (ceil-rank p99, censored +inf for drops) evaluated as multi-window
    burn rates against per-TPUServingJob ``spec.slo`` targets,
    emitting ``slo_burn`` DECISIONs onto BOTH the owning job's timeline
    and the offending requests' own, plus ``serving_slo_*`` families.

``events_per_request=0`` disables recording entirely; every seam checks
``recorder is None`` or finds ``record()`` returning immediately, and
the seeded chaos/fleet goldens stay byte-identical either way (the
recorder never writes to the seeded log).

Served as JSON at ``/debug/requests/<ns>/<name>[/<rid>]``
(cmd/health.py), rendered by ``tpu-jobs requests NS NAME``, and merged
into the ``/debug/traces`` Chrome-trace export as one lane per request
(category ``request``).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine import timeline as _timeline

# Events that are DECISIONS (routing/failure-handling verdicts about the
# request) vs routine progress traffic.  Classification is by EVENT here
# — unlike the job recorder's by-source split — because one source (the
# router) emits both classes: `dispatched` is cadence, `hedge_lost` is
# the record the timeline exists to remember.
_DECISION_EVENTS = frozenset({
    "hedge_issued", "hedge_won", "hedge_lost",
    "redispatch", "redispatch_skipped", "dispatch_failed",
    "degraded_entry", "degraded_exit",
    "memory_gate_block", "rejected", "drop",
    "duplicate_completion", "slo_burn",
})
# Events that close a timeline: the request became eligible for LRU
# eviction, and its milestones feed the SLO windows (censored +inf when
# it never delivered).
_TERMINAL_EVENTS = frozenset({"finished", "rejected", "drop"})
# Chrome-trace lane ids for request timelines start here — above the
# serving-telemetry block (1 << 20) and the job-timeline block
# (1 << 24), so the three lane families never alias in a merged export.
_LANE_TID_BASE = 1 << 25
# Minimum spacing between `progress` records per (request, replica):
# the fleet simulator reports token progress every step, and unbounded
# progress chatter would evict the admission/first-token records that
# give the timeline its shape.
_PROGRESS_MIN_GAP_S = 1.0
# Multi-window burn evaluation: both windows need this many samples
# before they can page (a single slow request must not), and a given
# (job, axis) re-fires at most once per half fast-window.
_SLO_MIN_SAMPLES = 5
_SLO_MAX_SAMPLES = 4096
_SLO_OFFENDERS_CAP = 10
_SLO_AXES = ("ttft", "tpot", "queue_wait", "e2e")


def _window_gated(vals: Sequence[Any]) -> bool:
    """Whether a burn window has enough evidence to page.  The
    min-sample gate suppresses noise-burns off a thin window — but a
    NON-EMPTY window whose every sample is censored (+inf: drops,
    scrape-storm casualties) is a total outage, the one regime where
    few samples is itself the signal.  Gate on (enough samples) OR
    (all of them censored), so a storm that strands two requests still
    pages instead of silently skipping the evaluation.  Accepts the
    pager's (value, rid) windows and the status snapshot's bare
    value windows."""
    if len(vals) >= _SLO_MIN_SAMPLES:
        return True
    return bool(vals) and all(
        math.isinf(v[0] if isinstance(v, tuple) else v) for v in vals
    )


class _ReqTimeline:
    """One request's rings + milestone bookkeeping, guarded by its own
    lock."""

    __slots__ = (
        "job_key", "rid", "lock", "events", "decisions", "seq", "last_ts",
        "finished", "dropped", "attempts", "attempt_of", "last_progress",
        "submitted_ts", "dispatched_ts", "admitted_ts", "first_token_ts",
        "finished_ts", "tokens",
    )

    def __init__(self, job_key: str, rid: str, cap: int) -> None:
        self.job_key = job_key
        self.rid = rid
        self.lock = threading.Lock()
        # two rings, one sequence: progress chatter cannot evict the
        # rare decision records that explain it
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self.decisions: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self.seq = 0
        self.last_ts = 0.0
        self.finished = False
        self.dropped = False
        # each `dispatched` record opens attempt N (0-based); replica ->
        # attempt lets later records (first_token via r2, hedge_won via
        # r2) attribute themselves to the arm that owns that replica
        self.attempts = 0
        self.attempt_of: Dict[str, int] = {}
        self.last_progress: Dict[str, float] = {}
        self.submitted_ts: Optional[float] = None
        self.dispatched_ts: Optional[float] = None
        self.admitted_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.finished_ts: Optional[float] = None
        self.tokens: Optional[int] = None


class _SloState:
    """One job's SLO targets + per-axis sliding sample windows, guarded
    by the recorder's slo lock."""

    __slots__ = ("spec", "samples", "last_burn", "last_eval")

    def __init__(self, spec: Any) -> None:
        self.spec = spec
        # axis -> deque[(ts, value, rid)]; pruned to the slow window on
        # every observe/evaluate, hard-capped so a burst cannot grow it
        self.samples: Dict[str, "deque[Tuple[float, float, str]]"] = {
            axis: deque(maxlen=_SLO_MAX_SAMPLES) for axis in _SLO_AXES
        }
        self.last_burn: Dict[str, float] = {}
        # last sample-driven window evaluation: scanning + ranking both
        # windows on EVERY finish is the recorder's one O(window) cost,
        # so finish-driven evals are spaced at least fast_window/2 apart
        # (slo_tick — the scrape cadence — always evaluates)
        self.last_eval = -math.inf


def _spec_targets(spec: Any) -> List[Tuple[str, float]]:
    """(axis, target_seconds) pairs for the targets the spec sets."""
    pairs = (
        ("ttft", getattr(spec, "ttft_p99_s", None)),
        ("tpot", getattr(spec, "tpot_p99_s", None)),
        ("queue_wait", getattr(spec, "queue_wait_p99_s", None)),
        ("e2e", getattr(spec, "e2e_p99_s", None)),
    )
    return [(axis, float(t)) for axis, t in pairs if t is not None]


def _p99(values: List[float]) -> Optional[float]:
    """Ceil-rank p99 (PR 14/15 convention); None on empty input."""
    if not values:
        return None
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, math.ceil(0.99 * len(ordered)) - 1)]


class RequestRecorder:
    """Thread-safe bounded per-request flight recorder + windowed SLO
    burn-rate engine.  See module docs."""

    def __init__(
        self,
        events_per_request: int = 128,
        max_requests: int = 2048,
        clock=time.time,
        job_recorder: Optional[_timeline.FlightRecorder] = None,
    ) -> None:
        self.events_per_request = int(events_per_request)
        self.max_requests = max(1, int(max_requests))
        self.clock = clock
        # where slo_burn DECISIONs about the JOB land; None falls back
        # to the process-global job recorder at emission time
        self.job_recorder = job_recorder
        self._requests: Dict[Tuple[str, str], _ReqTimeline] = {}
        # directory lock: first-contact admission + eviction ONLY — the
        # per-record hot path reads the dict without it (GIL-atomic) and
        # synchronizes on the request's own ring lock
        self._dir_lock = threading.Lock()
        self._slo: Dict[str, _SloState] = {}
        self._slo_lock = threading.Lock()
        # metric staging: the exporter families are global-locked and
        # label-keyed, too heavy for the per-record path — counts stage
        # here and flush on the scrape cadence (slo_tick) and on every
        # read entry point, so anything that LOOKS at the recorder sees
        # settled counters
        self._stats_lock = threading.Lock()
        self._pending_events: Dict[str, int] = {}
        self._pending_evictions = 0

    @property
    def enabled(self) -> bool:
        return self.events_per_request > 0

    # --------------------------------------------------------------- record
    def record(
        self,
        job_key: str,
        request_id: str,
        source: str,
        event: str,
        detail: Optional[Dict[str, Any]] = None,
        ts: Optional[float] = None,
    ) -> None:
        """Append one structured record to the request's ring.  O(1)
        under the request's ring lock; a disabled recorder returns
        immediately so every seam can stay unconditional behind a None
        check."""
        if self.events_per_request <= 0 or not job_key or not request_id:
            return
        if ts is None:
            ts = self.clock()
        if detail is None:
            detail = {}
        key = (job_key, str(request_id))
        samples: Optional[Dict[str, float]] = None
        while True:
            tl = self._requests.get(key)
            if tl is None:
                tl = self._admit(key)
            with tl.lock:
                if self._requests.get(key) is not tl:
                    # lost a race with _evict_locked between the lookup
                    # and the lock: appending to the orphaned ring would
                    # silently drop the record — re-admit and retry
                    continue
                if event == "progress":
                    rep = str(detail.get("replica", ""))
                    last = tl.last_progress.get(rep)
                    if last is not None and ts - last < _PROGRESS_MIN_GAP_S:
                        return
                    tl.last_progress[rep] = ts
                tl.seq += 1
                attempt, samples = self._apply_locked(tl, event, detail, ts)
                rec: Dict[str, Any] = {
                    "seq": tl.seq,
                    "t": ts,
                    "source": source,
                    "event": event,
                    "detail": detail,
                }
                if attempt is not None:
                    rec["attempt"] = attempt
                ring = (
                    tl.decisions if event in _DECISION_EVENTS else tl.events
                )
                ring.append(rec)
                tl.last_ts = ts
            break
        with self._stats_lock:
            self._pending_events[source] = (
                self._pending_events.get(source, 0) + 1
            )
        if samples:
            # SLO windows are fed OUTSIDE the ring lock: the evaluator
            # records slo_burn back onto request rings, and feeding it
            # under a ring lock would order ring -> slo -> ring
            self._slo_observe(job_key, key[1], samples, ts)

    def _apply_locked(
        self, tl: _ReqTimeline, event: str, detail: Dict[str, Any],
        ts: float,
    ) -> Tuple[Optional[int], Optional[Dict[str, float]]]:
        """Attempt attribution + milestone bookkeeping, one frame for
        both (the per-record path runs at fleet rates).  `dispatched`
        opens a new attempt owned by its replica; every other record
        joins the attempt that owns the replica it names (via
        `replica`, `via`, or `from`).  Returns (attempt, slo_samples) —
        samples only on the FIRST terminal record, censored +inf where
        the request never got that far."""
        if event == "dispatched":
            attempt: Optional[int] = tl.attempts
            tl.attempts += 1
            rep = detail.get("replica")
            if rep is not None:
                tl.attempt_of[str(rep)] = attempt
            if tl.dispatched_ts is None:
                tl.dispatched_ts = ts
            return attempt, None
        attempt = None
        for field in ("replica", "via", "from"):
            rep = detail.get(field)
            if rep is not None:
                attempt = tl.attempt_of.get(str(rep))
                if attempt is not None:
                    break
        return attempt, self._derive_locked(tl, event, detail, ts)

    def _derive_locked(
        self, tl: _ReqTimeline, event: str, detail: Dict[str, Any],
        ts: float,
    ) -> Optional[Dict[str, float]]:
        """Milestone bookkeeping; returns the SLO samples a terminal
        record yields (censored +inf where the request never got that
        far), None otherwise."""
        if event == "submitted" and tl.submitted_ts is None:
            tl.submitted_ts = ts
        elif event == "admitted" and tl.admitted_ts is None:
            tl.admitted_ts = ts
        elif event == "first_token" and tl.first_token_ts is None:
            tl.first_token_ts = ts
        if event not in _TERMINAL_EVENTS or tl.finished:
            return None
        tl.finished = True
        tl.finished_ts = ts
        if event != "finished":
            tl.dropped = True
        tokens = detail.get("tokens")
        if isinstance(tokens, (int, float)):
            tl.tokens = int(tokens)
        return self._samples_locked(tl, ts)

    @staticmethod
    def _samples_locked(tl: _ReqTimeline, ts: float) -> Dict[str, float]:
        """Latency samples at finish.  Censoring (PR 15 convention): a
        dropped/rejected request contributes +inf on every axis it never
        completed — a drop IS the worst latency, not a missing sample."""
        base = tl.submitted_ts
        out: Dict[str, float] = {}
        if base is None:
            return out
        if tl.dropped:
            out["e2e"] = math.inf
        else:
            out["e2e"] = max(0.0, ts - base)
        admit = tl.admitted_ts or tl.dispatched_ts
        if admit is not None:
            out["queue_wait"] = max(0.0, admit - base)
        elif tl.dropped:
            out["queue_wait"] = math.inf
        if tl.first_token_ts is not None:
            out["ttft"] = max(0.0, tl.first_token_ts - base)
            if not tl.dropped and tl.tokens and tl.tokens > 1:
                out["tpot"] = max(
                    0.0, (ts - tl.first_token_ts) / (tl.tokens - 1)
                )
        elif tl.dropped:
            out["ttft"] = math.inf
        return out

    # ------------------------------------------------------------ directory
    def _admit(self, key: Tuple[str, str]) -> _ReqTimeline:
        with self._dir_lock:
            tl = self._requests.get(key)
            if tl is not None:
                return tl
            if len(self._requests) >= self.max_requests:
                self._evict_locked()
            tl = _ReqTimeline(key[0], key[1], self.events_per_request)
            self._requests[key] = tl
            return tl

    def _evict_locked(self) -> None:
        """Evict the least-recently-touched FINISHED request.  In-flight
        requests are never evicted: their count is bounded by the fleet's
        admission caps, and a silent hole in a live timeline is worse
        than the memory."""
        victim_key = None
        victim_ts = None
        for key, tl in self._requests.items():
            if tl.finished and (victim_ts is None or tl.last_ts < victim_ts):
                victim_key, victim_ts = key, tl.last_ts
        if victim_key is not None:
            # delete UNDER the victim's ring lock — same identity-recheck
            # contract as the job recorder: an append either lands before
            # the eviction or observes the removal and re-admits
            with self._requests[victim_key].lock:
                del self._requests[victim_key]
            with self._stats_lock:
                self._pending_evictions += 1

    def _flush_stats(self) -> None:
        """Drain the staged per-source event counts into the exporter
        families.  Called on the scrape cadence (slo_tick) and from
        every read entry point — the counters are settled whenever
        anything observes the recorder, while the per-record hot path
        pays one small-lock dict bump instead of a global-locked
        label-keyed inc."""
        with self._stats_lock:
            if not self._pending_events and not self._pending_evictions:
                return
            pending, self._pending_events = self._pending_events, {}
            evictions, self._pending_evictions = self._pending_evictions, 0
        for source, n in pending.items():
            metrics.SERVING_REQUEST_TIMELINE_EVENTS.inc(
                {"source": source}, amount=n
            )
        if evictions:
            metrics.SERVING_REQUEST_TIMELINE_EVICTIONS.inc(
                amount=evictions
            )

    # ----------------------------------------------------------- SLO engine
    def set_slo(self, job_key: str, spec: Any) -> None:
        """Install (or clear, spec=None) a job's SLO targets.  `spec` is
        duck-typed to api/servingjob.SLOSpec: per-axis p99 targets plus
        objective / fast_window_s / slow_window_s / burn_threshold."""
        with self._slo_lock:
            if spec is None:
                self._slo.pop(job_key, None)
                return
            state = self._slo.get(job_key)
            if state is None:
                self._slo[job_key] = _SloState(spec)
            else:
                # retargeting keeps the accumulated windows: the samples
                # are ground truth regardless of where the bar sits
                state.spec = spec

    def _slo_observe(
        self, job_key: str, rid: str, samples: Dict[str, float], ts: float,
    ) -> None:
        with self._slo_lock:
            state = self._slo.get(job_key)
            if state is None:
                return
            targeted = {axis for axis, _ in _spec_targets(state.spec)}
            for axis, value in samples.items():
                if axis in targeted:
                    state.samples[axis].append((ts, value, rid))
            # space finish-driven evaluations out: a burst of finishes
            # must not rank the full windows per sample.  The gap equals
            # the burn cooldown (fast_window/2), so it cannot lower the
            # fire rate; worst added detection latency is one gap, and
            # only when no scrape loop is ticking slo_tick.
            gap = max(1.0, float(
                getattr(state.spec, "fast_window_s", 60.0)) / 2.0)
            if ts - state.last_eval < gap:
                return
            state.last_eval = ts
        self._slo_eval(job_key, ts)

    def slo_tick(self, now: Optional[float] = None) -> None:
        """Re-evaluate every job's windows (scrape-loop cadence): burn
        rates must decay when traffic stops, not freeze at their last
        finish-driven value."""
        if self.events_per_request <= 0:
            return
        if now is None:
            now = self.clock()
        self._flush_stats()
        with self._slo_lock:
            keys = list(self._slo)
            for state in self._slo.values():
                state.last_eval = now
        for job_key in keys:
            self._slo_eval(job_key, now)

    def _slo_eval(self, job_key: str, now: float) -> None:
        """Evaluate one job's multi-window burn rates; emissions happen
        after the slo lock drops (they take ring locks)."""
        emit: List[Tuple[str, Dict[str, Any], List[str]]] = []
        with self._slo_lock:
            state = self._slo.get(job_key)
            if state is None:
                return
            spec = state.spec
            fast_w = float(getattr(spec, "fast_window_s", 60.0))
            slow_w = float(getattr(spec, "slow_window_s", 300.0))
            objective = float(getattr(spec, "objective", 0.99))
            threshold = float(getattr(spec, "burn_threshold", 1.0))
            budget = max(1e-9, 1.0 - objective)
            for axis, target in _spec_targets(spec):
                dq = state.samples[axis]
                while dq and dq[0][0] < now - slow_w:
                    dq.popleft()
                slow = [(v, rid) for _, v, rid in dq]
                fast = [
                    (v, rid) for t, v, rid in dq if t >= now - fast_w
                ]
                burns: Dict[str, float] = {}
                for window, vals in (("fast", fast), ("slow", slow)):
                    if vals:
                        bad = sum(1 for v, _ in vals if v > target)
                        burns[window] = (bad / len(vals)) / budget
                    else:
                        burns[window] = 0.0
                    metrics.SERVING_SLO_BURN_RATE.set(
                        burns[window],
                        {"serving_job": job_key, "axis": axis,
                         "window": window},
                    )
                    p99 = _p99([v for v, _ in vals])
                    labels = {"serving_job": job_key, "axis": axis,
                              "window": window}
                    if p99 is not None and math.isfinite(p99):
                        metrics.SERVING_SLO_WINDOW_P99.set(p99, labels)
                    else:
                        # censored +inf (or no samples): an absent
                        # series IS the signal — never export inf/NaN
                        metrics.SERVING_SLO_WINDOW_P99.remove(labels)
                burning = (
                    _window_gated(fast)
                    and _window_gated(slow)
                    and burns["fast"] >= threshold
                    and burns["slow"] >= threshold
                )
                if not burning:
                    continue
                last = state.last_burn.get(axis)
                if last is not None and now - last < fast_w / 2.0:
                    continue  # cooldown: re-fire at most 2x per fast window
                state.last_burn[axis] = now
                slow_p99 = _p99([v for v, _ in slow])
                detail = {
                    "axis": axis,
                    "target_s": target,
                    "burn_fast": round(burns["fast"], 4),
                    "burn_slow": round(burns["slow"], 4),
                    "threshold": threshold,
                    "window_p99_s": (
                        round(slow_p99, 6)
                        if slow_p99 is not None and math.isfinite(slow_p99)
                        else None
                    ),
                    "samples_fast": len(fast),
                    "samples_slow": len(slow),
                }
                # offenders: the fast window's violators, newest first —
                # the requests whose timelines explain THIS burn
                offenders: List[str] = []
                for v, rid in reversed(fast):
                    if v > target and rid not in offenders:
                        offenders.append(rid)
                    if len(offenders) >= _SLO_OFFENDERS_CAP:
                        break
                emit.append((axis, detail, offenders))
        for axis, detail, offenders in emit:
            metrics.SERVING_SLO_BURNS.inc(
                {"serving_job": job_key, "axis": axis}
            )
            jr = self.job_recorder or _timeline.get_recorder()
            jr.record(job_key, "slo", "slo_burn", dict(detail), ts=now)
            for rid in offenders:
                self.record(
                    job_key, rid, "slo", "slo_burn", dict(detail), ts=now
                )

    def slo_status(self, job_key: str) -> Optional[Dict[str, Any]]:
        """Per-axis snapshot for `describe` / debug endpoints: target,
        both burn rates, slow-window p99 (None while censored), sample
        counts, and whether the multi-window condition holds right now."""
        self._flush_stats()
        now = self.clock()
        with self._slo_lock:
            state = self._slo.get(job_key)
            if state is None:
                return None
            spec = state.spec
            fast_w = float(getattr(spec, "fast_window_s", 60.0))
            slow_w = float(getattr(spec, "slow_window_s", 300.0))
            objective = float(getattr(spec, "objective", 0.99))
            threshold = float(getattr(spec, "burn_threshold", 1.0))
            budget = max(1e-9, 1.0 - objective)
            axes: Dict[str, Any] = {}
            for axis, target in _spec_targets(spec):
                dq = state.samples[axis]
                slow = [v for t, v, _ in dq if t >= now - slow_w]
                fast = [v for t, v, _ in dq if t >= now - fast_w]
                burns = {}
                for window, vals in (("fast", fast), ("slow", slow)):
                    bad = sum(1 for v in vals if v > target)
                    burns[window] = (bad / len(vals)) / budget if vals else 0.0
                p99 = _p99(slow)
                axes[axis] = {
                    "target_s": target,
                    "burn_fast": round(burns["fast"], 4),
                    "burn_slow": round(burns["slow"], 4),
                    "p99_s": (
                        round(p99, 6)
                        if p99 is not None and math.isfinite(p99)
                        else None
                    ),
                    "samples": len(slow),
                    # same gate as the pager (_slo_eval): a snapshot
                    # that says "not burning" during a total outage
                    # would contradict the burn the pager just fired
                    "burning": (
                        _window_gated(fast)
                        and _window_gated(slow)
                        and burns["fast"] >= threshold
                        and burns["slow"] >= threshold
                    ),
                }
            return {
                "objective": objective,
                "fast_window_s": fast_w,
                "slow_window_s": slow_w,
                "burn_threshold": threshold,
                "axes": axes,
            }

    # --------------------------------------------------------------- reads
    def jobs(self) -> List[str]:
        self._flush_stats()
        with self._dir_lock:
            return sorted({job for job, _ in self._requests})

    def request_ids(self, job_key: str) -> List[str]:
        with self._dir_lock:
            return sorted(
                rid for job, rid in self._requests if job == job_key
            )

    @staticmethod
    def _merged_locked(tl: _ReqTimeline) -> List[Dict[str, Any]]:
        """Both rings interleaved back into one sequence (caller holds
        tl.lock) — the single merge every export shares."""
        return sorted(
            (dict(e) for e in (*tl.events, *tl.decisions)),
            key=lambda e: e["seq"],
        )

    @staticmethod
    def _milestones_locked(tl: _ReqTimeline) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        base = tl.submitted_ts
        for name, ts in (
            ("submitted", tl.submitted_ts),
            ("dispatched", tl.dispatched_ts),
            ("admitted", tl.admitted_ts),
            ("first_token", tl.first_token_ts),
            ("finished", tl.finished_ts),
        ):
            if ts is not None:
                out[f"{name}_t"] = ts
                if base is not None and name != "submitted":
                    out[f"{name}_rel_s"] = round(ts - base, 6)
        if tl.tokens is not None:
            out["tokens"] = tl.tokens
        return out

    def _summary_locked(self, tl: _ReqTimeline) -> Dict[str, Any]:
        return {
            "request": tl.rid,
            "finished": tl.finished,
            "dropped": tl.dropped,
            "attempts": tl.attempts,
            "records": len(tl.events) + len(tl.decisions),
            "milestones": self._milestones_locked(tl),
        }

    def requests(self, job_key: str) -> List[Dict[str, Any]]:
        """Summaries of every tracked request of one job, ordered by
        submit time (the /debug/requests/<ns>/<name> payload)."""
        self._flush_stats()
        with self._dir_lock:
            keys = sorted(k for k in self._requests if k[0] == job_key)
        out = []
        for key in keys:
            tl = self._requests.get(key)
            if tl is None:
                continue
            with tl.lock:
                out.append(self._summary_locked(tl))
        out.sort(
            key=lambda s: (
                s["milestones"].get("submitted_t", 0.0), s["request"],
            )
        )
        return out

    def request_timeline(
        self, job_key: str, request_id: str
    ) -> Optional[Dict[str, Any]]:
        """One request's full merged timeline as a JSON-ready dict, or
        None when it was never recorded (or has been evicted)."""
        self._flush_stats()
        tl = self._requests.get((job_key, str(request_id)))
        if tl is None:
            return None
        with tl.lock:
            return {
                "job": tl.job_key,
                "request": tl.rid,
                "finished": tl.finished,
                "dropped": tl.dropped,
                "attempts": tl.attempts,
                "milestones": self._milestones_locked(tl),
                "events": self._merged_locked(tl),
            }

    def to_dict(self) -> Dict[str, Any]:
        """Every tracked timeline (the SIGUSR1 / --trace-dump payload)."""
        out: Dict[str, Dict[str, Any]] = {}
        for job_key in self.jobs():
            reqs = {
                rid: tl
                for rid in self.request_ids(job_key)
                if (tl := self.request_timeline(job_key, rid)) is not None
            }
            out[job_key] = {
                "requests": reqs,
                "slo": self.slo_status(job_key),
            }
        return {"jobs": out}

    def dump(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh)

    # -------------------------------------------------------------- export
    def chrome_events(
        self, per_request: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        """One Chrome-trace lane per request, merged into /debug/traces
        beside the reconcile spans, serving lanes, and job timelines
        (cat "request"): records carrying a duration (prefill chunks)
        render as complete events, the rest as instants, and each lane
        is named after its job + request id.  `per_request` keeps only
        each lane's newest N records — ?limit=N must bound the request
        recorder's contribution too."""
        self._flush_stats()
        events: List[Dict[str, Any]] = []
        pid = os.getpid()
        with self._dir_lock:
            items = sorted(self._requests.items())
        for lane, (key, tl) in enumerate(items, start=_LANE_TID_BASE + 1):
            with tl.lock:
                snapshot = self._merged_locked(tl)
            if per_request is not None and per_request >= 0:
                snapshot = snapshot[-per_request:] if per_request > 0 else []
            if not snapshot:
                continue
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": lane,
                "args": {"name": f"req {key[0]} {key[1]}"},
            })
            for e in snapshot:
                args = {"source": e["source"], "seq": e["seq"],
                        **(e["detail"] or {})}
                if "attempt" in e:
                    args["attempt"] = e["attempt"]
                dur = (e["detail"] or {}).get("duration")
                base = {
                    "name": e["event"], "cat": "request",
                    "ts": e["t"] * 1e6, "pid": pid, "tid": lane,
                    "args": args,
                }
                if isinstance(dur, (int, float)) and dur > 0:
                    events.append({
                        **base, "ph": "X", "ts": (e["t"] - dur) * 1e6,
                        "dur": dur * 1e6,
                    })
                else:
                    events.append({**base, "ph": "i", "s": "t"})
        return events


# disabled until an operator configures one (cmd/manager.
# build_request_recorder): the fallback the health endpoints and the
# in-process CLI read when no explicit recorder was injected — mirrors
# timeline.get_recorder()
_GLOBAL = RequestRecorder(events_per_request=0)


def get_recorder() -> RequestRecorder:
    return _GLOBAL


def set_recorder(recorder: RequestRecorder) -> None:
    """Register the process's request recorder (one per process, like
    the job recorder) so /debug endpoints and the in-process CLI find it
    without explicit wiring."""
    global _GLOBAL
    _GLOBAL = recorder
