"""Warm-pool pod placement — pre-provisioned standby slices.

The simulated ``create_to_running`` path is milliseconds, but a real TPU
pod cold-starts in minutes: image pull, runtime init, mesh bootstrap.
Speculative Container Scheduling (PAPERS.md, arXiv 2010.11307) removes
that latency from the critical path by placing containers *before* the
scheduler commits; this module is that idea as an operator subsystem:

  - The pool keeps **K pre-pulled, pre-initialized standby pods per slice
    shape** (v5e-1 / v5e-8 / v5e-256).  Standby pods are created ahead of
    demand, pay the image-pull + init latency while nobody is waiting,
    and sit Running (pre-warmed generic runtime) until claimed.
  - Job pod creation **claims** a warm pod instead of cold-creating when
    one is ready: a single compare-and-swap ``update`` that writes the
    job's controllerRef + labels in one shot, conditioned on the pod's
    resourceVersion.  Under sharding (or two operator processes) exactly
    one contender wins a contested pod — the loser's CAS conflicts, it
    falls back to the next pool pod or a cold create, and its
    expectations ledger is never touched.  A sharded engine additionally
    stamps its slot's **fencing token** into the claim body, so a zombie
    shard that lost its lease cannot claim pods for jobs it no longer
    owns (the store rejects the write with 403 before it lands).
  - Pool pods are **unowned until claimed** (no ownerReferences): they
    belong to no job and no shard, so a shard crash neither strands nor
    double-claims them — claimed pods become ordinary dependents that
    failover re-adopts like any other.
  - **Replenishment is asynchronous** and rides the existing slow-start
    fan-out (engine/fanout.py): refills never queue behind reconciles on
    a workqueue, a failing apiserver is probed with one create instead of
    a herd (the ramp aborts on first failure), and a per-shape capped-
    exponential retry ladder gates the next attempt so an error storm
    never produces runaway creates past K.

Workload identity is **late-bound**: a claimed pod keeps its (immutable)
spec — the standby image is the generic pre-warmed runtime — and the
job-specific cluster-spec env rides in annotations for the in-container
bootstrap to pick up (the model of the speculative-scheduling paper;
``runtime/bootstrap.py`` reads the same env contract).  Pods are indexed
by labels, not names, throughout the engine, so a claimed pod named
``warm-v5e-8-3`` serves replica index 2 exactly like a cold pod named
``{job}-worker-2`` would.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine.fanout import slow_start_batch
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.fake import ApiError, ConflictError, NotFoundError
from tf_operator_tpu.k8s.informer import capped_exponential

# Pool membership + provenance: present (value = slice shape) on every pod
# born in the pool; kept after a claim so warm-claimed replicas remain
# distinguishable (the cold-vs-warm histogram label and the soak audits
# key on it).  An UNCLAIMED pool pod = this label AND no controllerRef.
WARM_POOL_LABEL = "warm-pool-shape"
# A job/pod template opts into a slice shape with this annotation (or
# label); absent means DEFAULT_SHAPE — the single-host slice every plain
# job maps to.
SHAPE_ANNOTATION = "kubeflow.org/slice-shape"
# Claim CAS bookkeeping, written by the claiming engine in the claim body:
#   warm-claim: unique token the engine registered BEFORE issuing the
#     write — the MODIFIED event carrying it is the claim's "creation
#     observed" signal for the expectations ledger (a claim raises the
#     same ledger entry a create would, and the informer-delivered claim
#     event settles it the way an ADDED settles a create).
#   warm-bound-name / warm-bound-env: the replica identity + cluster-spec
#     env the pod would have carried had it been cold-created — the
#     late-binding contract the pre-warmed runtime reads.
WARM_CLAIM_ANNOTATION = "kubeflow.org/warm-claim"
WARM_BOUND_NAME_ANNOTATION = "kubeflow.org/warm-bound-name"
WARM_BOUND_ENV_ANNOTATION = "kubeflow.org/warm-bound-env"

DEFAULT_SHAPE = "v5e-1"
KNOWN_SHAPES = ("v5e-1", "v5e-8", "v5e-256")


def slice_shape_of(template: Dict[str, Any]) -> str:
    """The slice shape a pod template requests: the SHAPE_ANNOTATION from
    its metadata (annotation first, label as a fallback), else
    DEFAULT_SHAPE.  Pure so the engine and the pool always agree."""
    meta = template.get("metadata", {}) or {}
    for source in (meta.get("annotations"), meta.get("labels")):
        shape = (source or {}).get(SHAPE_ANNOTATION)
        if shape:
            return shape
    return DEFAULT_SHAPE


def is_warm_pool_pod(obj: Dict[str, Any]) -> bool:
    return WARM_POOL_LABEL in objects.labels_of(obj)


def is_unclaimed_pool_pod(obj: Dict[str, Any]) -> bool:
    return is_warm_pool_pod(obj) and objects.get_controller_of(obj) is None


@dataclass
class WarmPoolConfig:
    # shape -> K standby pods to keep pre-provisioned
    sizes: Dict[str, int] = field(default_factory=dict)
    namespace: str = "default"
    # image the standby pods are pre-pulled with (the generic pre-warmed
    # runtime).  With match_any_image (the late-binding model) any job
    # image claims any warm pod of the right shape; without it, a claim
    # requires the job's image to equal the standby image — an image the
    # node never pulled has no pre-pull win to offer.
    image: str = "warm-runtime"
    match_any_image: bool = True
    # restartPolicy the standby pods are born with.  Pod spec is immutable
    # at claim time, so a claim requires the job template's EFFECTIVE
    # policy to equal this (controller.py forces ExitCode -> Never before
    # claiming; the operator's default replica policy is Never too) — a
    # mismatched standby would let the kubelet restart a failed container
    # in place, hiding exits the operator's restart accounting must see.
    restart_policy: str = "Never"
    # replenish retry ladder (per shape): first retry after retry_base,
    # doubling to retry_max — an apiserver error storm is probed, not
    # hammered
    retry_base: float = 1.0
    retry_max: float = 60.0


class WarmPoolManager:
    """Keeps the per-shape standby pools full and serves CAS claims.

    One instance per operator process, shared by every shard's engines
    (claims are rv-CAS-safe across processes; the in-process lock merely
    avoids self-contention).  ``replenish()`` is safe to call from a
    deterministic driver (the chaos harness steps it explicitly); threaded
    deployments call ``start()`` for the background refill loop, which
    also wakes promptly on every claim."""

    def __init__(
        self,
        cluster,
        config: WarmPoolConfig,
        clock=time.time,
        fanout: int = 1,
        refill_interval: float = 0.5,
        ready_probe=None,
    ) -> None:
        self.cluster = cluster
        self.config = config
        self.clock = clock
        self.fanout = max(1, fanout)
        self.refill_interval = refill_interval
        # optional extra readiness gate (runtime/bootstrap.py pre-warm
        # probe): a Running standby pod is claimable only once the probe
        # accepts it — e.g. the JAX runtime reports its persistent
        # compilation cache is primed.  None = phase Running is enough.
        self.ready_probe = ready_probe
        # job flight recorder (engine/timeline.py): when wired by the
        # manager, claim hits and misses land in the claiming job's
        # timeline with the reason — "why did this replica cold-start"
        # answered per job.  None disables the seam.
        self.recorder = None
        self._lock = threading.RLock()
        # shape -> {pod name -> last-known pod object} (unclaimed only;
        # Pending entries are "filling", Running entries are claimable)
        self._pool: Dict[str, Dict[str, Dict[str, Any]]] = {
            shape: {} for shape in config.sizes
        }
        self._seq: Dict[str, int] = {shape: 0 for shape in config.sizes}
        # replenish retry ladder state, per shape
        self._fail_count: Dict[str, int] = {}
        self._retry_at: Dict[str, float] = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # phase transitions / deletions / cross-process claims arrive as
        # pod events; our own creates are inserted directly (a watch
        # outage must not blind the deficit accounting into runaway
        # creates past K)
        cluster.subscribe("Pod", self._on_pod_event)

    # ------------------------------------------------------------- tracking
    def _on_pod_event(self, event_type: str, pod: Dict[str, Any]) -> None:
        labels = objects.labels_of(pod)
        shape = labels.get(WARM_POOL_LABEL)
        if shape is None:
            return
        name = objects.name_of(pod)
        with self._lock:
            pool = self._pool.get(shape)
            if pool is None:
                return
            if event_type == "DELETED" or objects.get_controller_of(pod):
                # gone, or claimed (possibly by another process): not ours
                # to hand out anymore
                pool.pop(name, None)
            else:
                # upsert even for names we have not inserted yet: on the
                # REST backend the watch can deliver the standby's Running
                # MODIFIED before our create call returns — dropping it
                # would store the stale Pending create-response and leave
                # the pod "filling" forever.  Unknown unclaimed pool pods
                # (another process's pool, resync gaps) are adopted here
                # exactly as resync() would adopt them.
                pool[name] = pod
            self._update_gauges_locked(shape)

    def _update_gauges_locked(self, shape: str) -> None:
        pool = self._pool.get(shape, {})
        ready = sum(1 for p in pool.values() if self._is_ready(p))
        metrics.WARM_POOL_SIZE.set(ready, {"shape": shape, "state": "ready"})
        metrics.WARM_POOL_SIZE.set(
            len(pool) - ready, {"shape": shape, "state": "filling"}
        )

    def _is_ready(self, pod: Dict[str, Any]) -> bool:
        # belt and braces: a tracked copy that already shows a
        # controllerRef is claimed no matter how it got here — CAS'ing
        # over it with a current rv would STEAL the rival's pod
        if objects.get_controller_of(pod) is not None:
            return False
        if objects.pod_phase(pod) != objects.POD_RUNNING:
            return False
        return self.ready_probe is None or bool(self.ready_probe(pod))

    def ready_count(self, shape: str) -> int:
        with self._lock:
            return sum(
                1 for p in self._pool.get(shape, {}).values()
                if self._is_ready(p)
            )

    def size(self, shape: str) -> int:
        """Unclaimed pool pods of the shape, ready + filling."""
        with self._lock:
            return len(self._pool.get(shape, {}))

    # ------------------------------------------------------------- lifecycle
    def resync(self) -> None:
        """Adopt pre-existing unclaimed pool pods (operator restart: the
        pool, like any dependent state, is rebuilt from the cluster)."""
        for shape in self.config.sizes:
            try:
                pods = self.cluster.list_pods(
                    namespace=self.config.namespace,
                    selector={WARM_POOL_LABEL: shape},
                )
            except (ApiError, OSError):
                continue  # the refill loop retries; startup must not die
            with self._lock:
                pool = self._pool.setdefault(shape, {})
                for pod in pods:
                    if objects.get_controller_of(pod) is None:
                        name = objects.name_of(pod)
                        pool.setdefault(name, pod)
                        # never reuse a discovered pod's sequence number
                        tail = name.rsplit("-", 1)[-1]
                        if tail.isdigit():
                            self._seq[shape] = max(
                                self._seq.get(shape, 0), int(tail) + 1
                            )
                self._update_gauges_locked(shape)

    def start(self) -> None:
        self.resync()
        self.replenish()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._refill_loop, name="warm-pool-refill", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        try:
            self.cluster.unsubscribe("Pod", self._on_pod_event)
        except Exception:  # noqa: BLE001 — best-effort detach on shutdown
            pass

    def _refill_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.refill_interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.replenish()
            except Exception:  # noqa: BLE001 — refill upkeep must not die
                pass

    # ------------------------------------------------------------- replenish
    def _standby_pod(self, shape: str, name: str) -> Dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": self.config.namespace,
                "labels": {WARM_POOL_LABEL: shape},
                "annotations": {SHAPE_ANNOTATION: shape},
            },
            # deliberately NO ownerReferences: unowned until claimed
            "spec": {
                "restartPolicy": self.config.restart_policy,
                "containers": [
                    {"name": "warm-runtime", "image": self.config.image}
                ],
            },
            "status": {"phase": objects.POD_PENDING},
        }

    def _reap_terminal(self) -> None:
        """Delete unclaimed standbys stuck in a terminal phase (pre-warm
        runtime exited, chaos OOM): the deficit math counts them, so left
        alone they would depress the ready pool below K forever."""
        with self._lock:
            reap = [
                (shape, name, objects.namespace_of(p))
                for shape, pool in self._pool.items()
                for name, p in sorted(pool.items())
                if objects.pod_phase(p)
                in (objects.POD_SUCCEEDED, objects.POD_FAILED)
            ]
        for shape, name, ns in reap:
            try:
                self.cluster.delete_pod(ns, name)
            except NotFoundError:
                pass
            except (ApiError, OSError):
                continue  # still tracked; retried next cycle
            with self._lock:
                self._pool.get(shape, {}).pop(name, None)
                self._update_gauges_locked(shape)

    def replenish(self) -> int:
        """Top every shape's pool back up to K.  Deficit counts ready AND
        filling pods, so creates never overshoot; shapes inside their
        retry-ladder window are skipped.  Returns pods created."""
        self._reap_terminal()
        now = self.clock()
        plan: List[tuple] = []
        with self._lock:
            for shape, k in self.config.sizes.items():
                if now < self._retry_at.get(shape, 0.0):
                    continue
                deficit = k - len(self._pool.get(shape, {}))
                for _ in range(max(0, deficit)):
                    name = f"warm-{shape}-{self._seq[shape]}"
                    self._seq[shape] += 1
                    plan.append((shape, name))
        if not plan:
            return 0

        failed_shapes: Dict[str, BaseException] = {}

        def create_one(shape: str, name: str) -> None:
            created = self.cluster.create_pod(self._standby_pod(shape, name))
            with self._lock:
                # insert directly: the pod event may be gated (chaos watch
                # outage) and the deficit math must still see it.
                # setdefault, not assignment: the watch may already have
                # delivered a FRESHER copy (Running) than this create
                # response, and overwriting it would regress the pod to
                # Pending in our book.
                self._pool.setdefault(shape, {}).setdefault(name, created)
                self._update_gauges_locked(shape)
            metrics.WARM_POOL_REPLENISH.inc({"shape": shape})

        res = slow_start_batch(
            [lambda s=s, n=n: create_one(s, n) for s, n in plan],
            self.fanout,
            abort_on_failure=True,  # probe a failing apiserver, don't herd
        )
        for idx, err in res.failures:
            failed_shapes.setdefault(plan[idx][0], err)
        with self._lock:
            touched = {s for s, _ in plan}
            for shape in touched:
                if shape in failed_shapes:
                    n = self._fail_count.get(shape, 0)
                    self._fail_count[shape] = n + 1
                    self._retry_at[shape] = self.clock() + capped_exponential(
                        self.config.retry_base, n, self.config.retry_max
                    )
                else:
                    self._fail_count.pop(shape, None)
                    self._retry_at.pop(shape, None)
        return res.successes

    # ------------------------------------------------------------- claims
    def try_claim(
        self,
        namespace: str,
        shape: str,
        image: str,
        labels: Dict[str, str],
        annotations: Dict[str, str],
        controller_ref: Dict[str, Any],
        fence_token: Optional[str] = None,
        restart_policy: Optional[str] = None,
        node_hint: Optional[str] = None,
    ) -> Optional[Dict[str, Any]]:
        """Claim one ready warm pod of `shape` for a job replica, or None
        (caller falls back to a cold create).  The claim is ONE update:
        controllerRef + the replica's full label set + the late-binding
        annotations, CAS'd on the pod's resourceVersion — under contention
        exactly one claimer wins; a loser's conflict re-reads once (the
        bump may have been a kubelet status write, not a rival claim) and
        then moves to the next candidate.  A sharded caller passes its
        fencing token; the store rejects a stale one with 403, which
        propagates so the engine's fenced-mid-sync handling runs.

        `restart_policy` is the template's EFFECTIVE pod restartPolicy
        (after the ExitCode -> Never rewrite): the pod spec is immutable,
        so a standby born with a different policy is never claimable — a
        kubelet honoring the wrong policy would restart failed containers
        in place and hide exits from the operator's restart accounting.

        `node_hint` is the cluster scheduler's speculative-placement seam
        (the member's reserved node): standbys already sitting on the
        hinted node are tried first — claiming one makes the speculative
        placement exact — but any ready standby still beats a cold
        create.  Ordering stays a pure function of pool state + hint, so
        seeded chaos runs replay identically.

        Misses are counted once per reason per call, and only when the
        whole claim falls back cold (docs/monitoring.md: a miss == a
        fallback, so warm_hit_ratio can be read off claims vs misses)."""
        t0 = self.clock()
        with self._lock:
            pool = self._pool.get(shape, {})
            # sorted: the claim order is a function of pool state, not
            # dict insertion interleaving — seeded chaos runs replay it
            candidates = sorted(
                name for name, pod in pool.items() if self._is_ready(pod)
            )
            if node_hint:
                candidates.sort(
                    key=lambda name: (
                        0 if (
                            (pool.get(name, {}).get("spec") or {})
                            .get("nodeName") == node_hint
                        ) else 1,
                        name,
                    )
                )
        miss_reasons = set()
        for name in candidates:
            with self._lock:
                pod = self._pool.get(shape, {}).get(name)
            if pod is None:
                # claimed/deleted since the snapshot: lost to a rival
                miss_reasons.add("contested")
                continue
            if objects.namespace_of(pod) != namespace:
                miss_reasons.add("namespace")
                continue
            spec = pod.get("spec", {}) or {}
            pod_image = (spec.get("containers") or [{}])[0].get("image", "")
            if not self.config.match_any_image and pod_image != image:
                miss_reasons.add("image_mismatch")
                continue
            if (
                restart_policy is not None
                and spec.get("restartPolicy") != restart_policy
            ):
                miss_reasons.add("restart_policy")
                continue
            claimed = self._cas_claim(
                shape, name, pod, labels, annotations, controller_ref,
                fence_token,
            )
            if claimed is not None:
                metrics.WARM_POOL_CLAIMS.inc({"shape": shape})
                metrics.CREATE_TO_RUNNING.observe(
                    max(0.0, self.clock() - t0), {"path": "warm"}
                )
                self._record_claim(
                    namespace, labels, "warm_claim",
                    {"shape": shape, "pod": name,
                     "node": (claimed.get("spec") or {}).get("nodeName")},
                )
                self._wake.set()  # refill the hole promptly
                return claimed
            miss_reasons.add("contested")
        if not candidates:
            miss_reasons.add("empty")
        for reason in sorted(miss_reasons):
            metrics.WARM_POOL_CLAIM_MISSES.inc(
                {"shape": shape, "reason": reason}
            )
        if miss_reasons:
            # one timeline record per fallback, like the metric: the
            # claiming job's story says why it paid a cold create
            self._record_claim(
                namespace, labels, "warm_miss",
                {"shape": shape, "reasons": sorted(miss_reasons)},
            )
        return None

    def _record_claim(
        self, namespace: str, labels: Dict[str, str], event: str,
        detail: Dict[str, Any],
    ) -> None:
        """Flight-recorder seam: the claiming job's identity rides the
        replica label set the claim writes, so the record lands in the
        right job's timeline without new plumbing."""
        if self.recorder is None:
            return
        job_name = labels.get(objects.LABEL_JOB_NAME)
        if job_name:
            self.recorder.record(
                f"{namespace}/{job_name}", "warmpool", event, detail,
                ts=self.clock(),
            )

    def _cas_claim(
        self,
        shape: str,
        name: str,
        pod: Dict[str, Any],
        labels: Dict[str, str],
        annotations: Dict[str, str],
        controller_ref: Dict[str, Any],
        fence_token: Optional[str],
        retried: bool = False,
    ) -> Optional[Dict[str, Any]]:
        from tf_operator_tpu.engine.sharding import FENCE_ANNOTATION

        if objects.get_controller_of(pod) is not None:
            # already someone's dependent — never overwrite a rival claim
            with self._lock:
                self._pool.get(shape, {}).pop(name, None)
                self._update_gauges_locked(shape)
            return None
        body = objects.fast_deepcopy(pod)
        meta = body.setdefault("metadata", {})
        meta["ownerReferences"] = [objects.fast_deepcopy(controller_ref)]
        meta.setdefault("labels", {}).update(labels)
        ann = meta.setdefault("annotations", {})
        ann.update(annotations)
        if fence_token:
            ann[FENCE_ANNOTATION] = fence_token
        try:
            out = self.cluster.update_pod(body)
        except ConflictError:
            # rv moved under us: a rival claim, or just a kubelet status
            # write.  One fresh read decides — still unclaimed retries the
            # CAS once on the new rv; claimed/other means we lost the pod.
            try:
                fresh = self.cluster.get_pod(objects.namespace_of(pod), name)
            except (NotFoundError, ApiError):
                fresh = None
            if (
                fresh is not None
                and not retried
                and objects.get_controller_of(fresh) is None
            ):
                with self._lock:
                    if name in self._pool.get(shape, {}):
                        self._pool[shape][name] = fresh
                return self._cas_claim(
                    shape, name, fresh, labels, annotations, controller_ref,
                    fence_token, retried=True,
                )
            with self._lock:
                self._pool.get(shape, {}).pop(name, None)
                self._update_gauges_locked(shape)
            return None
        except NotFoundError:
            with self._lock:
                self._pool.get(shape, {}).pop(name, None)
                self._update_gauges_locked(shape)
            return None
        with self._lock:
            self._pool.get(shape, {}).pop(name, None)
            self._update_gauges_locked(shape)
        return out
