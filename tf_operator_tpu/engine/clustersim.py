"""One cluster, one day: the trace-driven mixed train+serve tenancy
harness (PR 16).

Every subsystem below has its own bench — the gang scheduler
(BENCH_r07), warm pools (r06), elastic resize (r11), the serving fleet
and its failure domain (r13/r14), the request SLO engine (r15) — but
none of them ever shared a Node.  This module composes ALL of them on
one FakeCluster inventory and replays one simulated day:

  * a diurnal serving curve (models/fleetsim.make_trace: late-heavy
    session arrivals, burst windows, heavy-tailed prompts) served by a
    TPUServingJob fleet whose autoscaler must ACQUIRE chips from the
    shared ClusterScheduler before every scale-out (the
    ``FleetHarness.capacity`` gate) — serving grows into capacity that
    training is not using, and not one chip further;
  * a tenant mix of training gangs (high-priority rigid, low-priority
    elastic with a min-replicas floor) driven by a deliberately small
    gang controller: submit -> gang admission -> pods -> Running,
    observing evictions/kills through the pods exactly like the real
    engine, executing scheduler-requested shrinks through the
    resize-drain-resume path, and re-queueing after preemption;
  * a seeded mid-day CHAOS window riding the r14 FaultInjector: a
    fleet-wide scrape storm, a replica freeze (SIGSTOP'd decode), a
    kill-mid-decode, a ``kill -9`` of the scheduler control-plane
    worker (state rebuilt from pods via resync, the r10 contract), and
    a node drain THROUGH the scheduler (which cordons the node until
    the chaos script uncordons it).

Scoring is the two flight recorders: engine/timeline.FlightRecorder
per-job SLOs (time-to-running, restart MTTR, resize duration) and
engine/reqtrace.RequestRecorder burn windows + the fleet summary
(TTFT/drops).  Everything is a pure function of the seed: the injector
log, the router log, and the scheduler notes merge into one
deterministic transcript whose sha256 the bench asserts across runs.

The HARDENED arm runs the full stack (shrink-before-evict, hedged
re-dispatch, scrape-failure ejection); the BASELINE arm switches all
three off.  Same trace, same chaos, same seed — the delta is the PR 16
headline: the hardened day serves every request and recovers every
gang; the baseline day drops requests on the frozen replica and
strands the evicted low-priority gang.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tf_operator_tpu.api.servingjob import AutoscaleSpec, SLOSpec
from tf_operator_tpu.engine.reqtrace import RequestRecorder
from tf_operator_tpu.engine.scheduler import (
    ASSIGNED_NODE_ANNOTATION,
    MIN_REPLICAS_ANNOTATION,
    PRIORITY_ANNOTATION,
    SLICE_SHAPE_LABEL,
    ClusterScheduler,
)
from tf_operator_tpu.engine.timeline import FlightRecorder
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.chaos import FaultInjector, SimClock
from tf_operator_tpu.k8s.fake import FakeCluster
from tf_operator_tpu.models.fleetsim import FleetHarness, make_trace

NS = "default"
TRAIN_KIND = "TFJob"
SERVE_KIND = "TPUServingJob"
SERVE_NAME = "serve"
SERVE_KEY = f"{NS}/{SERVE_NAME}"
SERVE_UID = "uid-serve"


@dataclass
class GangSpec:
    """One training tenant.  ``min_replicas`` set => elastic (the
    scheduler may shrink it to the floor instead of evicting);
    ``work_s`` set => the gang finishes after that much full-width
    progress and releases its slice (None = trains past the horizon)."""

    name: str
    replicas: int
    priority: int
    chips: int = 8
    min_replicas: Optional[int] = None
    submit_at: float = 0.0
    work_s: Optional[float] = None


@dataclass
class ChaosDay:
    """The seeded mid-day failure storm (absolute sim seconds).  Any
    field set to None skips that injection, so tests can run partial
    storms without re-deriving the whole timeline."""

    scrape_storm_at: Optional[float] = 100.0
    scrape_storm_s: float = 15.0
    freeze_at: Optional[float] = 125.0          # SIGSTOP replica r0
    kill_decode_at: Optional[float] = 140.0     # newest live replica
    blackout_at: Optional[float] = 160.0        # kill -9 the scheduler
    blackout_s: float = 20.0
    drain_at: Optional[float] = 200.0
    drain_node: str = "n1"   # first training node under packed placement
    uncordon_at: Optional[float] = 240.0


class _Gang:
    """Runtime state of one training tenant: the minimal gang
    controller.  States: unsubmitted -> pending -> starting -> running
    (-> repairing -> running | -> resizing -> starting | -> pending on
    eviction) -> done."""

    def __init__(self, spec: GangSpec) -> None:
        self.spec = spec
        self.uid = f"uid-{spec.name}"
        self.key = f"{NS}/{spec.name}"
        self.state = "unsubmitted"
        self.width = spec.replicas      # current target gang width
        self.restarts = 0               # member deaths observed via pods
        self.requeue_at = 0.0
        self.resize_done_at = 0.0
        self.progress = 0.0
        self.last_run_ts: Optional[float] = None

    def member(self, i: int) -> str:
        # name-type-index: the format the scheduler's elastic shrink
        # planner parses to find droppable high indices
        return f"{self.spec.name}-worker-{i}"

    def members(self) -> Dict[str, int]:
        return {self.member(i): self.spec.chips for i in range(self.width)}


class _ServingCapacity:
    """The ``FleetHarness.capacity`` gate: every serving scale-out must
    win a one-member gang admission from the shared scheduler first.
    Admission CAN preempt (a traffic spike shrinks the elastic
    low-priority tenant through the same verb a training arrival would
    use), but the gate yields outright while a training gang of equal
    or higher priority is pending — APF semantics: the serving fleet
    must not starve a parked high-priority gang by grabbing freed chips
    one replica at a time.  Denials ride the autoscaler's own cooldown,
    so a yielded scale-out is re-attempted, not flapped."""

    def __init__(self, sim: "ClusterDaySim") -> None:
        self.sim = sim
        self.uids: Dict[str, str] = {}          # live rid -> reservation uid
        self._granted: Optional[Tuple[str, str]] = None

    def acquire(self, now: float) -> bool:
        sim = self.sim
        if sim.sched is None:
            return False                        # control plane is dead
        for gang in sim.gangs:
            if (
                gang.state == "pending"
                and gang.spec.priority >= sim.serve_priority
            ):
                sim.inj.note(
                    f"serve_yield gang={gang.key} "
                    f"priority={gang.spec.priority}"
                )
                return False
        rid = f"r{sim.fleet._next_idx}"         # the next _add_replica id
        member = f"serve-{rid}"
        uid = f"{SERVE_UID}-{rid}"
        ok, _msg = sim.sched.admit(
            job_key=SERVE_KEY, job_uid=uid, kind=SERVE_KIND, namespace=NS,
            members={member: sim.serve_chips}, priority=sim.serve_priority,
        )
        if not ok:
            # the autoscaler polls; a parked pending entry would just
            # hold the gauge up between its cooldown-spaced attempts
            sim.sched.release(uid)
            return False
        self._granted = (uid, member)
        return True

    def bind(self, rid: str) -> None:
        assert self._granted is not None
        uid, member = self._granted
        self._granted = None
        self.uids[rid] = uid
        node = self.sim.sched.planned_node(uid, member)
        self.sim._create_serving_pod(member, node)

    def release(self, rid: str) -> None:
        uid = self.uids.pop(rid, None)
        if uid is None:
            return
        if self.sim.sched is not None:
            self.sim.sched.release(uid)
        self.sim._delete_pod(f"serve-{rid}")


class ClusterDaySim:
    """One shared-inventory simulated day.  ``hardened`` arms
    shrink-before-evict + hedging + ejection; the baseline switches all
    three off.  Everything else — trace, chaos, inventory — is
    identical, so the scored delta is exactly the hardening."""

    def __init__(
        self,
        seed: int = 0,
        hardened: bool = True,
        nodes: int = 6,
        node_shape: str = "v5e-8",
        gangs: Optional[List[GangSpec]] = None,
        serve_chips: int = 8,
        serve_priority: int = 100,
        serve_max_replicas: int = 3,
        n_users: int = 260,
        trace_horizon_s: float = 300.0,
        horizon_s: float = 420.0,
        base_rate: float = 1.0,
        burst_rate: float = 7.0,
        bursts: Tuple[Tuple[float, float], ...] = ((60.0, 25.0), (240.0, 18.0)),
        chaos: Optional[ChaosDay] = None,
        dt: float = 0.05,
        train_sync_s: float = 0.25,
        resize_drain_s: float = 2.0,
        requeue_backoff_s: float = 1.0,
        slo_tick_s: float = 5.0,
        pod_start_delay: float = 1.0,
    ) -> None:
        self.seed = seed
        self.hardened = hardened
        self.horizon_s = horizon_s
        self.dt = dt
        self.train_sync_s = train_sync_s
        self.resize_drain_s = resize_drain_s
        self.requeue_backoff_s = requeue_backoff_s
        self.slo_tick_s = slo_tick_s
        self.serve_chips = serve_chips
        self.serve_priority = serve_priority
        self.node_shape = node_shape
        self.chaos = chaos

        self.cluster = FakeCluster()
        self.clock = SimClock()
        self.inj = FaultInjector(
            self.cluster, seed=seed, clock=self.clock, kubelet=True,
            pod_start_delay=pod_start_delay, nodes=nodes,
        )
        self.node_names = [f"n{i}" for i in range(nodes)]
        for name in self.node_names:
            self.cluster.add_node(name, shape=node_shape)

        self.frec = FlightRecorder(clock=self.clock)
        self.rrec = RequestRecorder(clock=self.clock, job_recorder=self.frec)
        self.sched: Optional[ClusterScheduler] = self._make_scheduler()
        self.sched.resync()   # nodes predate the scheduler's watch
        self.inj.scheduler = self.sched
        self.inj.recorder = self.frec
        # evictions booked by a scheduler incarnation that later died
        # (the blackout): carried forward so the restart cross-check
        # spans the whole day, not just the surviving process
        self._evictions_carry: Dict[str, int] = {}

        self.gangs = [
            _Gang(s) for s in (gangs or [
                GangSpec("train-high", replicas=2, priority=100,
                         submit_at=0.5),
                GangSpec("train-low", replicas=3, priority=10,
                         min_replicas=1, submit_at=1.0),
            ])
        ]

        # the serving job CR: resync reads priority (and the absent
        # elastic floor) from here when rebuilding replica reservations
        self.cluster.create(SERVE_KIND, {
            "apiVersion": "kubeflow.org/v1", "kind": SERVE_KIND,
            "metadata": {
                "name": SERVE_NAME, "namespace": NS, "uid": SERVE_UID,
                "annotations": {PRIORITY_ANNOTATION: str(serve_priority)},
            },
            "spec": {},
        })
        self.fleet = FleetHarness(
            mode="occupancy",
            n_replicas=1,
            # floor of TWO: hedged re-dispatch needs a sibling, so the
            # autoscaler must never drain the fleet down to one replica
            # that might be the frozen one (the scale-in victim picker
            # cannot see a SIGSTOP'd decode behind healthy heartbeats)
            autoscale=AutoscaleSpec(
                min_replicas=2, max_replicas=serve_max_replicas,
                scale_out_queue_wait_p99_s=2.0,
                scale_out_blocked_admissions=4,
                scale_in_occupancy_floor=0.2,
            ),
            warm_standbys=2,
            injector=self.inj,
            hedging=hardened,
            ejection=hardened,
            recorder=self.frec,
            job_key=SERVE_KEY,
            reqtrace=self.rrec,
            slo=SLOSpec(ttft_p99_s=6.0, queue_wait_p99_s=5.0,
                        fast_window_s=30.0, slow_window_s=120.0),
            dt=dt,
        )
        self.capacity = _ServingCapacity(self)
        self.fleet.capacity = self.capacity
        # the constructor's initial replica (r0) predates the gate:
        # adopt its reservation so day-zero serving capacity is booked
        # against the shared inventory like everything after it
        ok, msg = self.sched.admit(
            job_key=SERVE_KEY, job_uid=f"{SERVE_UID}-r0", kind=SERVE_KIND,
            namespace=NS, members={"serve-r0": serve_chips},
            priority=serve_priority,
        )
        if not ok:
            raise RuntimeError(f"initial serving replica unplaceable: {msg}")
        self.capacity.uids["r0"] = f"{SERVE_UID}-r0"
        self._create_serving_pod(
            "serve-r0",
            self.sched.planned_node(f"{SERVE_UID}-r0", "serve-r0"),
        )
        self.frec.record(SERVE_KEY, "controller", "created",
                         {"kind": SERVE_KIND}, uid=SERVE_UID, ts=0.0)

        self.trace = make_trace(
            seed, n_users=n_users, horizon_s=trace_horizon_s,
            base_rate=base_rate, burst_rate=burst_rate, bursts=bursts,
        )
        self.blackout_events = 0
        if chaos is not None:
            self._schedule_chaos(chaos)

    # ------------------------------------------------------------ plumbing
    def _make_scheduler(self) -> ClusterScheduler:
        sched = ClusterScheduler(
            self.inj, policy="packed", clock=self.clock,
            note=self.inj.note, shrink_before_evict=self.hardened,
        )
        sched.recorder = self.frec
        return sched

    def _delete_pod(self, name: str) -> None:
        try:
            self.inj.delete_pod(NS, name)
        except Exception:  # noqa: BLE001 — already gone / storm: fine
            pass

    def _pod(self, name: str, node: Optional[str], job_name: str,
             kind: str, uid: str, chips: int, replica_type: str) -> Dict[str, Any]:
        shape = f"v5e-{chips}"
        return {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": name, "namespace": NS,
                "labels": {
                    objects.LABEL_JOB_NAME: job_name,
                    objects.LABEL_REPLICA_TYPE: replica_type,
                },
                "annotations": {
                    ASSIGNED_NODE_ANNOTATION: node or "",
                    SLICE_SHAPE_LABEL: shape,
                },
                "ownerReferences": [{
                    "apiVersion": "kubeflow.org/v1", "kind": kind,
                    "name": job_name, "uid": uid, "controller": True,
                }],
            },
            "spec": {
                "nodeName": node or "",
                "containers": [{"name": "main"}],
            },
            "status": {"phase": objects.POD_PENDING},
        }

    def _create_serving_pod(self, member: str,
                            node: Optional[str]) -> None:
        # owned by the CR itself (its uid must be live or the fake's GC
        # reaps the pod at birth); the per-replica reservation uid is
        # scheduler-side bookkeeping only
        self.inj.create_pod(self._pod(
            member, node, SERVE_NAME, SERVE_KIND, SERVE_UID,
            self.serve_chips, "replica",
        ))

    def _gang_pod(self, gang: _Gang, member: str) -> None:
        node = (
            self.sched.planned_node(gang.uid, member)
            if self.sched is not None else None
        )
        self.inj.create_pod(self._pod(
            member, node, gang.spec.name, TRAIN_KIND, gang.uid,
            gang.spec.chips, "worker",
        ))

    def _gang_pods(self, gang: _Gang) -> List[Dict[str, Any]]:
        out = []
        for i in range(gang.width):
            try:
                out.append(self.inj.get_pod(NS, gang.member(i)))
            except Exception:  # noqa: BLE001 — missing/storm reads as gone
                out.append(None)
        return out

    # --------------------------------------------------------------- chaos
    def _schedule_chaos(self, c: ChaosDay) -> None:
        if c.scrape_storm_at is not None:
            self.inj.schedule_scrape_storm(
                c.scrape_storm_at, c.scrape_storm_s, mode="timeout",
            )
        if c.freeze_at is not None:
            self.inj.schedule_replica_freeze(c.freeze_at, "r0")
        if c.kill_decode_at is not None:
            self.inj.at(
                c.kill_decode_at, self._kill_newest_replica,
                "kill_mid_decode replica=newest",
            )
        if c.blackout_at is not None:
            self.inj.at(
                c.blackout_at, self._blackout_begin,
                "control_plane_kill proc=scheduler signal=9",
            )
            self.inj.at(
                c.blackout_at + c.blackout_s, self._blackout_end,
                "control_plane_respawn proc=scheduler",
            )
        if c.drain_at is not None:
            self.inj.at(
                c.drain_at,
                lambda: self.inj.drain_node(c.drain_node),
                f"drain_begin node={c.drain_node}",
            )
        if c.uncordon_at is not None:
            self.inj.at(
                c.uncordon_at,
                lambda: self.sched is not None
                and self.sched.uncordon(c.drain_node),
                f"uncordon node={c.drain_node}",
            )

    def _kill_newest_replica(self) -> None:
        # pick at fire time: the newest live autoscaled replica (never
        # r0 — that one is the freeze target).  Deterministic: fleet
        # state at the firing tick is a pure function of the seed.
        live = [
            rid for rid, r in self.fleet.replicas.items()
            if r.alive and rid != "r0" and rid not in self.fleet._starting
        ]
        if live:
            self.fleet.kill_now(max(live, key=lambda rid: int(rid[1:])))

    def _blackout_begin(self) -> None:
        # kill -9: the scheduler's in-memory reservations die with it.
        # Admission, resize completion, and eviction detection all stall
        # until the respawn resyncs from pods (the r10 contract: derived
        # state is rebuilt, not replicated).
        if self.sched is not None:
            for key, n in self.sched.evictions.items():
                self._evictions_carry[key] = (
                    self._evictions_carry.get(key, 0) + n
                )
        self.sched = None
        self.inj.scheduler = None
        self.blackout_events += 1

    def _blackout_end(self) -> None:
        sched = self._make_scheduler()
        sched.resync()
        # resync rebuilt the serving fleet as ONE reservation under the
        # CR uid (every replica pod shares the CR's ownerRef): the
        # serving side now re-asserts its per-replica reservations,
        # adopting each pod's live placement — the same first-sync
        # re-admission the training controllers do after a respawn
        sched.release(SERVE_UID)
        for rid in sorted(self.capacity.uids, key=lambda r: int(r[1:])):
            member = f"serve-{rid}"
            try:
                pod = self.inj.get_pod(NS, member)
            except Exception:  # noqa: BLE001 — died mid-blackout
                self.capacity.uids.pop(rid, None)
                continue
            node = (pod.get("spec") or {}).get("nodeName") or None
            sched.admit(
                job_key=SERVE_KEY, job_uid=self.capacity.uids[rid],
                kind=SERVE_KIND, namespace=NS,
                members={member: self.serve_chips},
                priority=self.serve_priority,
                existing={member: node} if node else None,
            )
        self.sched = sched
        self.inj.scheduler = sched
        self.inj.note("scheduler_resync complete")

    # ------------------------------------------------------ gang controller
    def _train_tick(self, now: float) -> None:
        for gang in self.gangs:
            if gang.state == "done" or now < gang.spec.submit_at:
                continue
            if gang.state == "unsubmitted":
                self._submit_gang(gang, now)
                continue
            if self.sched is None:
                # control-plane blackout: pods still run (kubelet is
                # alive) but nothing can be admitted, shrunk, or
                # detected as evicted — observation-only below
                if gang.state == "starting":
                    self._check_all_running(gang, now)
                elif gang.state == "running":
                    self._account_progress(gang, now)
                continue
            if gang.state in ("starting", "running", "repairing", "resizing"):
                if self.sched.reserved_members(gang.uid) == 0:
                    self._on_evicted(gang, now)
                    continue
            if gang.state in ("running", "repairing"):
                if self._maybe_start_shrink(gang, now):
                    continue
            if gang.state == "pending":
                if now >= gang.requeue_at:
                    self._try_admit(gang, now)
            elif gang.state == "starting":
                self._check_all_running(gang, now)
            elif gang.state == "repairing":
                self._check_repaired(gang, now)
            elif gang.state == "resizing":
                if now >= gang.resize_done_at:
                    self._finish_shrink(gang, now)
            elif gang.state == "running":
                self._observe_member_failures(gang, now)
                if gang.state == "running":
                    self._account_progress(gang, now)
                    self._maybe_complete(gang, now)

    def _submit_gang(self, gang: _Gang, now: float) -> None:
        ann = {PRIORITY_ANNOTATION: str(gang.spec.priority)}
        if gang.spec.min_replicas is not None:
            ann[MIN_REPLICAS_ANNOTATION] = str(gang.spec.min_replicas)
        self.inj.create(TRAIN_KIND, {
            "apiVersion": "kubeflow.org/v1", "kind": TRAIN_KIND,
            "metadata": {
                "name": gang.spec.name, "namespace": NS,
                "uid": gang.uid, "annotations": ann,
            },
            "spec": {"tfReplicaSpecs": {
                "Worker": {"replicas": gang.spec.replicas},
            }},
        })
        self.frec.record(gang.key, "controller", "created",
                         {"kind": TRAIN_KIND}, uid=gang.uid, ts=now)
        gang.state = "pending"
        gang.requeue_at = now

    def _spec_replicas(self, gang: _Gang) -> int:
        try:
            cr = self.inj.get(TRAIN_KIND, NS, gang.spec.name)
        except Exception:  # noqa: BLE001 — storm: keep last known width
            return gang.width
        return int(
            ((cr.get("spec") or {}).get("tfReplicaSpecs") or {})
            .get("Worker", {}).get("replicas") or gang.width
        )

    def _maybe_start_shrink(self, gang: _Gang, now: float) -> bool:
        """The scheduler's shrink-before-evict patched our spec down: run
        the elastic resize path — drain, then re-admit at the floor.
        Capacity frees when the smaller shape admits, exactly the
        failure-atomic verb (PR 11)."""
        target = self._spec_replicas(gang)
        if target >= gang.width:
            return False
        self._account_progress(gang, now)
        gang.state = "resizing"
        gang.resize_done_at = now + self.resize_drain_s
        self.frec.record(
            gang.key, "controller", "resize_requested",
            {"from": gang.width, "to": target}, uid=gang.uid, ts=now,
        )
        return True

    def _finish_shrink(self, gang: _Gang, now: float) -> None:
        target = self._spec_replicas(gang)
        dropped = list(range(target, gang.width))
        gang.width = max(target, gang.spec.min_replicas or 0)
        ok, _msg = self.sched.admit(
            job_key=gang.key, job_uid=gang.uid, kind=TRAIN_KIND,
            namespace=NS, members=gang.members(),
            priority=gang.spec.priority,
            min_replicas=gang.spec.min_replicas,
        )
        for i in dropped:
            # graceful scale-down, not a restart: the drained members'
            # pods exit clean and nobody books a kill
            self._delete_pod(gang.member(i))
        self.frec.record(
            gang.key, "controller", "resumed",
            {"replicas": gang.width, "admitted": bool(ok)},
            uid=gang.uid, ts=now,
        )
        gang.state = "starting" if ok else "pending"
        gang.requeue_at = now + self.requeue_backoff_s

    def _try_admit(self, gang: _Gang, now: float) -> None:
        ok, _msg = self.sched.admit(
            job_key=gang.key, job_uid=gang.uid, kind=TRAIN_KIND,
            namespace=NS, members=gang.members(),
            priority=gang.spec.priority,
            min_replicas=gang.spec.min_replicas,
        )
        if not ok:
            gang.requeue_at = now + self.requeue_backoff_s
            return
        for i in range(gang.width):
            self._delete_pod(gang.member(i))
            self._gang_pod(gang, gang.member(i))
        gang.state = "starting"

    def _check_all_running(self, gang: _Gang, now: float) -> None:
        pods = self._gang_pods(gang)
        if any(p is None for p in pods):
            return
        if all(objects.pod_phase(p) == objects.POD_RUNNING for p in pods):
            self.frec.record(
                gang.key, "controller", "condition",
                {"type": "Running", "reason": "AllReplicasRunning"},
                uid=gang.uid, ts=now,
            )
            self.frec.record(
                gang.key, "controller", "replicas_active",
                {"active": gang.width}, uid=gang.uid, ts=now,
            )
            gang.state = "running"
            gang.last_run_ts = now

    def _check_repaired(self, gang: _Gang, now: float) -> None:
        pods = self._gang_pods(gang)
        if any(p is None for p in pods):
            return
        if all(objects.pod_phase(p) == objects.POD_RUNNING for p in pods):
            # full strength again: replicas_active closes the MTTR clock
            # (the Running condition never flipped — partial degradation)
            self.frec.record(
                gang.key, "controller", "replicas_active",
                {"active": gang.width}, uid=gang.uid, ts=now,
            )
            gang.state = "running"
            gang.last_run_ts = now

    def _observe_member_failures(self, gang: _Gang, now: float) -> None:
        """A member died but the reservation survived (drain_keep, a
        stray chaos kill): ExitCode restart semantics — recreate the pod
        into its still-held slot."""
        failed = []
        for i, pod in enumerate(self._gang_pods(gang)):
            if pod is not None and objects.pod_phase(pod) == objects.POD_FAILED:
                failed.append(i)
        if not failed:
            return
        self._account_progress(gang, now)
        gang.restarts += len(failed)
        for i in failed:
            self._delete_pod(gang.member(i))
            self._gang_pod(gang, gang.member(i))
        gang.state = "repairing"

    def _on_evicted(self, gang: _Gang, now: float) -> None:
        """The whole reservation is gone (preemption or drain): every
        member died — count them, sweep the corpses, requeue the gang
        wholesale.  The failure marks (scheduler ``preempted`` / chaos
        ``kill`` / ``drain_evicted``) already opened the MTTR clock."""
        if gang.state != "resizing":
            self._account_progress(gang, now)
        gang.restarts += gang.width
        for i in range(gang.width):
            self._delete_pod(gang.member(i))
        gang.state = "pending"
        gang.requeue_at = now + self.requeue_backoff_s

    def _account_progress(self, gang: _Gang, now: float) -> None:
        if gang.last_run_ts is not None:
            gang.progress += now - gang.last_run_ts
        gang.last_run_ts = now

    def _maybe_complete(self, gang: _Gang, now: float) -> None:
        if gang.spec.work_s is None or gang.progress < gang.spec.work_s:
            return
        for i in range(gang.width):
            self._delete_pod(gang.member(i))
        if self.sched is not None:
            self.sched.release(gang.uid)
        self.frec.record(
            gang.key, "controller", "condition",
            {"type": "Succeeded", "reason": "Completed"},
            uid=gang.uid, ts=now,
        )
        gang.state = "done"

    # ---------------------------------------------------- serving reconcile
    def _serve_reconcile(self) -> None:
        """Kill fleet replicas whose cluster half died externally (node
        drain through the scheduler, a chaos pod kill): the router stops
        dispatching to them and the autoscaler re-acquires capacity
        through the gate."""
        if self.sched is None:
            return
        for rid in sorted(self.capacity.uids, key=lambda r: int(r[1:])):
            replica = self.fleet.replicas.get(rid)
            if replica is None or not replica.alive:
                continue
            if self.sched.reserved_members(self.capacity.uids[rid]) == 0:
                self.fleet.kill_now(rid)

    # ----------------------------------------------------------------- run
    def run(self) -> Dict[str, Any]:
        self.fleet.begin(self.trace, horizon_s=self.horizon_s)
        next_train = 0.0
        next_slo = 0.0
        while self.clock() < self.horizon_s:
            self.inj.step(self.dt)
            now = self.clock()
            if now >= next_train:
                next_train = now + self.train_sync_s
                self._train_tick(now)
                self._serve_reconcile()
            self.fleet.service_tick()
            if now >= next_slo:
                next_slo = now + self.slo_tick_s
                self.rrec.slo_tick(now)
        serving = self.fleet.finish()
        # finish() just recorded every unserved request as a censored
        # +inf drop: one last evaluation so a lost tail fires its burn
        # (the total-outage window rule) instead of expiring unseen
        self.rrec.slo_tick(self.clock())
        return self._score(serving)

    # --------------------------------------------------------------- score
    def _booked_restarts(self, gang: _Gang) -> int:
        booked = self._evictions_carry.get(gang.key, 0)
        if self.sched is not None:
            booked += self.sched.evictions.get(gang.key, 0)
        booked += self.inj.retryable_kills.get((gang.key, "worker"), 0)
        return booked

    def _slo_burns(self, job_key: str) -> int:
        tl = self.frec.timeline(job_key) or {}
        return sum(
            1 for e in tl.get("events", [])
            if e.get("source") == "slo" and e.get("event") == "slo_burn"
        )

    def transcript(self) -> str:
        """The full deterministic day: injector log (chaos + scheduler
        notes) and the fleet's merged router log, in one byte-stable
        document — what the bench hashes for the per-seed contract."""
        return (
            "\n".join(self.inj.log)
            + "\n-- fleet --\n"
            + "\n".join(self.fleet.log)
        )

    def _score(self, serving: Dict[str, Any]) -> Dict[str, Any]:
        gangs_out = []
        for gang in self.gangs:
            slo = self.frec.slo(gang.key) or {}
            gangs_out.append({
                "name": gang.spec.name,
                "priority": gang.spec.priority,
                "replicas": gang.spec.replicas,
                "min_replicas": gang.spec.min_replicas,
                "state": gang.state,
                "width": gang.width,
                "restarts_observed": gang.restarts,
                "restarts_booked": self._booked_restarts(gang),
                "time_to_running_s": slo.get("time_to_running_s"),
                "last_restart_mttr_s": slo.get("last_restart_mttr_s"),
                "last_resize_duration_s": slo.get("last_resize_duration_s"),
            })
        slo_axes = self.rrec.slo_status(SERVE_KEY) or {}
        digest = hashlib.sha256(self.transcript().encode()).hexdigest()
        return {
            "seed": self.seed,
            "hardened": self.hardened,
            "nodes": len(self.node_names),
            "requests": len(self.trace),
            "horizon_s": self.horizon_s,
            "serving": dict(
                serving,
                slo_burns=self._slo_burns(SERVE_KEY),
                slo_axes=slo_axes.get("axes", {}),
                scale_out_denied=sum(
                    1 for e in self.fleet.scale_events
                    if e["dir"] == "out_denied"
                ),
            ),
            "gangs": gangs_out,
            "chaos": {
                "blackouts": self.blackout_events,
                "kills": dict(self.inj.stats),
            },
            "log_sha256": digest,
        }


def run_cluster_day(seed: int = 0, hardened: bool = True,
                    **kwargs: Any) -> Dict[str, Any]:
    """One chaos day, scored.  The bench's entry point."""
    sim = ClusterDaySim(
        seed=seed, hardened=hardened,
        chaos=kwargs.pop("chaos", ChaosDay()), **kwargs,
    )
    return sim.run()
