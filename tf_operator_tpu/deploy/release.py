"""Release pipeline — image build/push, SDK wheel, release artifacts.

Reference parity: py/kubeflow/tf_operator/release.py (build_operator_image
:122, _push_image :223, write_build_info :278, build_and_push_artifacts
:239) rebuilt with a testable command plan and TPU-era defaults (one
python operator image instead of a Go binary + ECR mirror fan-out)."""
from __future__ import annotations

import io
import json
import os
import tarfile
import time
from dataclasses import dataclass
from typing import Dict, Optional

from tf_operator_tpu.deploy.render import render_overlay, to_yaml_stream
from tf_operator_tpu.deploy.runner import CommandRunner

DEFAULT_IMAGE_NAME = "tpu-training-operator"


def git_sha(runner: CommandRunner, repo_root: str, short: bool = True) -> str:
    argv = ["git", "-C", repo_root, "rev-parse"]
    if short:
        argv.append("--short=12")
    argv.append("HEAD")
    out = runner.run(argv).strip()
    return out or "dryrunsha"


def image_tag(version: str, sha: str) -> str:
    """vX.Y.Z-gSHA — reference tags images v{date}-{sha} (release.py:152);
    version+sha keeps tags unique AND sortable by release."""
    return f"v{version.lstrip('v')}-g{sha}"


@dataclass
class ReleaseConfig:
    repo_root: str
    registry: str  # e.g. gcr.io/my-project
    version: str = "0.1.0"
    image_name: str = DEFAULT_IMAGE_NAME
    dockerfile: str = "build/images/tpu-training-operator/Dockerfile"
    artifacts_dir: str = "dist"

    def image(self, sha: str) -> str:
        return f"{self.registry}/{self.image_name}:{image_tag(self.version, sha)}"

    def latest_image(self) -> str:
        return f"{self.registry}/{self.image_name}:latest"


def build_operator_image(runner: CommandRunner, cfg: ReleaseConfig,
                         sha: str) -> str:
    image = cfg.image(sha)
    runner.run([
        "docker", "build",
        "-t", image, "-t", cfg.latest_image(),
        "-f", os.path.join(cfg.repo_root, cfg.dockerfile),
        cfg.repo_root,
    ])
    return image


def push_image(runner: CommandRunner, cfg: ReleaseConfig, image: str) -> None:
    runner.run(["docker", "push", image])
    runner.run(["docker", "push", cfg.latest_image()])


def build_sdk_wheel(runner: CommandRunner, cfg: ReleaseConfig) -> str:
    """Build the installable package (pyproject.toml; reference publishes
    kubeflow-tfjob via sdk/python/setup.py:15)."""
    out_dir = os.path.join(cfg.repo_root, cfg.artifacts_dir)
    runner.run([
        "python", "-m", "pip", "wheel", "--no-deps",
        "-w", out_dir, cfg.repo_root,
    ])
    return out_dir


def write_build_info(cfg: ReleaseConfig, image: str, sha: str,
                     now: Optional[float] = None) -> str:
    """build_info.yaml equivalent (reference release.py:278-297): what was
    built, from which commit, when — consumed by CI to promote releases."""
    info = {
        "image": image,
        "commit": sha,
        "version": cfg.version,
        "timestamp": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(now if now is not None else time.time())
        ),
    }
    out_dir = os.path.join(cfg.repo_root, cfg.artifacts_dir)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "build_info.json")
    with open(path, "w") as f:
        json.dump(info, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def write_manifest_bundle(cfg: ReleaseConfig, image: str) -> str:
    """Render both overlays against the released image and tar them up —
    the install artifact a release ships alongside the image."""
    out_dir = os.path.join(cfg.repo_root, cfg.artifacts_dir)
    os.makedirs(out_dir, exist_ok=True)
    bundle = os.path.join(out_dir, "manifests.tar.gz")
    with tarfile.open(bundle, "w:gz") as tar:
        for overlay in ("standalone", "kubeflow"):
            docs = render_overlay(cfg.repo_root, overlay, image=image)
            payload = to_yaml_stream(docs).encode()
            ti = tarfile.TarInfo(name=f"manifests/{overlay}.yaml")
            ti.size = len(payload)
            ti.mtime = 0
            tar.addfile(ti, io.BytesIO(payload))
    return bundle


def release(runner: CommandRunner, cfg: ReleaseConfig, push: bool = False,
            write_artifacts: Optional[bool] = None) -> Dict[str, str]:
    """Full pipeline: image -> (push) -> wheel -> build info -> manifest
    bundle.  Returns the artifact map.

    write_artifacts defaults to `not runner.dry_run`: a dry run only
    prints the command plan and must not touch dist/ (it could clobber a
    previous real release's artifacts with a dryrunsha build info)."""
    if write_artifacts is None:
        write_artifacts = not runner.dry_run
    sha = git_sha(runner, cfg.repo_root)
    image = build_operator_image(runner, cfg, sha)
    if push:
        push_image(runner, cfg, image)
    artifacts = {
        "image": image,
        "sdk_wheel_dir": build_sdk_wheel(runner, cfg),
    }
    out_dir = os.path.join(cfg.repo_root, cfg.artifacts_dir)
    if write_artifacts:
        artifacts["build_info"] = write_build_info(cfg, image, sha)
        artifacts["manifest_bundle"] = write_manifest_bundle(cfg, image)
    else:
        artifacts["build_info"] = os.path.join(out_dir, "build_info.json") + " (not written: dry run)"
        artifacts["manifest_bundle"] = os.path.join(out_dir, "manifests.tar.gz") + " (not written: dry run)"
    return artifacts
