"""Cluster setup + operator deployment for GKE TPU slices.

Reference parity: py/kubeflow/tf_operator/deploy.py (setup_cluster :103,
teardown :260) — rebuilt for the TPU path: instead of GPU node pools, the
plan creates TPU slice node pools (one per accelerator type), since a
TPU multi-host slice maps to a dedicated GKE node pool whose nodes are
the slice's TPU VM hosts.  Operator install goes through the in-repo
kustomize renderer + the ClusterClient (k8s/client.py) when a kubeconfig
is given, or a kubectl plan otherwise."""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tf_operator_tpu.deploy.render import render_overlay, to_yaml_stream
from tf_operator_tpu.deploy.runner import CommandRunner

# acceleratorType prefix -> GKE machine type for the TPU VM hosts
TPU_MACHINE_TYPES = {
    "v4": "ct4p-hightpu-4t",
    "v5e": "ct5lp-hightpu-4t",
    "v5p": "ct5p-hightpu-4t",
    "v6e": "ct6e-standard-4t",
}


def tpu_nodepool_args(accelerator_type: str, topology: str = "") -> List[str]:
    """gcloud flags for one TPU slice node pool (e.g. v5p-128)."""
    gen = accelerator_type.split("-")[0]
    machine = TPU_MACHINE_TYPES.get(gen)
    if machine is None:
        raise ValueError(
            f"unknown TPU generation {gen!r} in acceleratorType "
            f"{accelerator_type!r} (known: {sorted(TPU_MACHINE_TYPES)})"
        )
    args = ["--machine-type", machine]
    if topology:
        args += ["--tpu-topology", topology]
    # slices are all-or-nothing: no autoscaling mid-slice
    args += ["--num-nodes", "1", "--placement-type", "COMPACT"]
    return args


@dataclass
class ClusterConfig:
    project: str
    zone: str
    name: str
    # acceleratorType -> topology ('' = let GKE derive)
    tpu_pools: Dict[str, str] = field(default_factory=dict)
    release_channel: str = "regular"


def setup_cluster(runner: CommandRunner, cfg: ClusterConfig) -> None:
    """Create the GKE cluster + one TPU node pool per accelerator type
    (reference setup_cluster creates a GPU cluster + installs drivers —
    TPU pools need no driver daemonset)."""
    runner.run([
        "gcloud", "container", "clusters", "create", cfg.name,
        "--project", cfg.project, "--zone", cfg.zone,
        "--release-channel", cfg.release_channel,
        "--num-nodes", "1",
    ])
    for acc, topo in cfg.tpu_pools.items():
        runner.run([
            "gcloud", "container", "node-pools", "create",
            f"tpu-{acc.replace('-', '')}",
            "--cluster", cfg.name,
            "--project", cfg.project, "--zone", cfg.zone,
            *tpu_nodepool_args(acc, topo),
        ])
    runner.run([
        "gcloud", "container", "clusters", "get-credentials", cfg.name,
        "--project", cfg.project, "--zone", cfg.zone,
    ])


def teardown_cluster(runner: CommandRunner, cfg: ClusterConfig) -> None:
    runner.run([
        "gcloud", "container", "clusters", "delete", cfg.name,
        "--project", cfg.project, "--zone", cfg.zone, "--quiet",
    ])


# ---------------------------------------------------------------- operator
def deploy_operator_kubectl(runner: CommandRunner, repo_root: str,
                            overlay: str = "standalone",
                            image: Optional[str] = None) -> None:
    """Apply the rendered overlay through kubectl (no client needed)."""
    stream = to_yaml_stream(render_overlay(repo_root, overlay, image=image))
    runner.run(["kubectl", "apply", "-f", "-"], input_text=stream)


def deploy_operator_client(cluster, repo_root: str,
                           overlay: str = "standalone",
                           image: Optional[str] = None) -> List[str]:
    """Apply the rendered overlay through a ClusterClient/FakeCluster
    (k8s/client.py surface): create-or-update by (kind, ns, name).
    Returns the applied object keys."""
    from tf_operator_tpu.k8s import objects
    from tf_operator_tpu.k8s.fake import NotFoundError

    applied = []
    for doc in render_overlay(repo_root, overlay, image=image):
        kind = doc.get("kind", "")
        # cluster-scoped objects key under the empty namespace
        # (objects.CLUSTER_SCOPED_KINDS via namespace_of)
        ns, name = objects.namespace_of(doc), objects.name_of(doc)
        try:
            existing = cluster.get(kind, ns, name)
        except NotFoundError:
            existing = None
        if existing is None:
            cluster.create(kind, doc)
        else:
            doc.setdefault("metadata", {})["resourceVersion"] = (
                existing.get("metadata", {}).get("resourceVersion")
            )
            cluster.update(kind, doc)
        applied.append(f"{kind}/{ns or '-'}/{name}")
    return applied


def wait_operator_ready(cluster, namespace: str = "tpu-operator-system",
                        name: str = "tpu-training-operator",
                        timeout_s: float = 300.0,
                        poll_s: float = 2.0,
                        clock=time.monotonic,
                        sleep=time.sleep) -> bool:
    """Poll the operator Deployment until readyReplicas >= 1 (reference
    deploy.py waits on the tf-job-operator deployment the same way)."""
    from tf_operator_tpu.k8s.fake import NotFoundError

    deadline = clock() + timeout_s
    while clock() < deadline:
        try:
            dep = cluster.get("Deployment", namespace, name)
        except NotFoundError:
            dep = None
        if dep and (dep.get("status", {}).get("readyReplicas") or 0) >= 1:
            return True
        sleep(poll_s)
    return False
