"""Deploy/release tooling — the equivalent of the reference's
py/kubeflow/tf_operator/{deploy,release}.py (cluster setup, operator
deploy, image build+push, release artifacts), rebuilt for GKE TPU
slices.  All shell-outs go through runner.CommandRunner so every plan is
dry-runnable and unit-testable without gcloud/docker/kubectl installed.
"""
