"""Injectable command execution for deploy/release tooling.

Every external command (gcloud, docker, kubectl, git) flows through
CommandRunner, so tests and --dry-run see the exact plan that real runs
execute (reference deploy.py/release.py shell out ad hoc via util.run,
which makes their plans untestable without a cluster)."""
from __future__ import annotations

import subprocess
from dataclasses import dataclass, field
from typing import List, Optional, Sequence


class CommandError(RuntimeError):
    def __init__(self, argv: Sequence[str], rc: int, output: str):
        super().__init__(f"command {list(argv)} failed with rc={rc}: {output[-500:]}")
        self.argv = list(argv)
        self.rc = rc
        self.output = output


@dataclass
class CommandRunner:
    """dry_run=True records commands and returns canned output; real mode
    shells out and raises CommandError on failure."""

    dry_run: bool = True
    log: List[List[str]] = field(default_factory=list)
    stdins: List[Optional[str]] = field(default_factory=list)
    echo: bool = False

    def run(self, argv: Sequence[str], *, input_text: Optional[str] = None,
            timeout: Optional[float] = None) -> str:
        self.log.append(list(argv))
        # keep the stdin payload so a dry-run plan shows WHAT would be
        # applied (e.g. the manifest stream behind `kubectl apply -f -`),
        # not just the command line
        self.stdins.append(input_text)
        if self.echo:
            print("+ " + " ".join(argv))
            if input_text:
                head = input_text[:400]
                print(f"  <<stdin ({len(input_text)} bytes)>> {head}"
                      + ("..." if len(input_text) > 400 else ""))
        if self.dry_run:
            return ""
        r = subprocess.run(
            list(argv), input=input_text, capture_output=True, text=True,
            timeout=timeout,
        )
        out = (r.stdout or "") + (r.stderr or "")
        if r.returncode != 0:
            raise CommandError(argv, r.returncode, out)
        return r.stdout or ""

    def plan(self) -> List[str]:
        return [
            " ".join(argv)
            + (f" <<stdin ({len(stdin)} bytes)>>" if stdin else "")
            for argv, stdin in zip(self.log, self.stdins)
        ]
