"""Minimal kustomize renderer — resolves this repo's manifest overlays to
one YAML stream without the kustomize binary.

Supports the subset our manifests use (and validates it's only that
subset): `resources` (files or directories containing kustomization.yaml),
`namespace`, `commonLabels`, `images` name/newName/newTag overrides, and
`patches` (strategic-merge patch files with a kind/name target).
The reference relies on `kubectl kustomize` (README.md:24); shipping the
renderer keeps deploy tooling and tests hermetic."""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, List, Optional

import yaml

SUPPORTED_KEYS = {
    "apiVersion", "kind", "resources", "namespace", "commonLabels", "images",
    "patches",
}

# cluster-scoped kinds never get a namespace stamped on them (shared
# scoping table: k8s/objects.py)
from tf_operator_tpu.k8s.objects import CLUSTER_SCOPED_KINDS as CLUSTER_SCOPED


def _load_yaml_docs(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def render_kustomization(path: str) -> List[Dict[str, Any]]:
    """Render the kustomization at `path` (a directory) to manifest dicts."""
    kfile = os.path.join(path, "kustomization.yaml")
    with open(kfile) as f:
        kust = yaml.safe_load(f) or {}
    unknown = set(kust) - SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"{kfile}: unsupported kustomization keys {sorted(unknown)} "
            f"(renderer supports {sorted(SUPPORTED_KEYS)})"
        )
    docs: List[Dict[str, Any]] = []
    for res in kust.get("resources", []) or []:
        rpath = os.path.normpath(os.path.join(path, res))
        if os.path.isdir(rpath):
            docs.extend(render_kustomization(rpath))
        else:
            docs.extend(_load_yaml_docs(rpath))
    ns = kust.get("namespace")
    if ns:
        for d in docs:
            if d.get("kind") not in CLUSTER_SCOPED:
                d.setdefault("metadata", {})["namespace"] = ns
            # kustomize also rewrites ServiceAccount subjects in role
            # bindings — without this the deployed operator's SA lives in
            # the overlay namespace while the binding points elsewhere,
            # and every operator API call 403s
            if d.get("kind") in ("RoleBinding", "ClusterRoleBinding"):
                for subj in d.get("subjects", []) or []:
                    if subj.get("kind") == "ServiceAccount":
                        subj["namespace"] = ns
    labels = kust.get("commonLabels") or {}
    if labels:
        for d in docs:
            md = d.setdefault("metadata", {})
            md["labels"] = {**(md.get("labels") or {}), **labels}
            _label_selectors_and_templates(d, labels)
    for img in kust.get("images", []) or []:
        _override_image(docs, img)
    for patch in kust.get("patches", []) or []:
        _apply_patch(docs, patch, path)
    return docs


def _strategic_merge(base: Any, patch: Any) -> Any:
    """Strategic-merge subset: dicts merge per key; lists whose elements all
    carry a `name` merge by it (k8s patchMergeKey for containers/ports/
    volumes/env); other lists replace."""
    if isinstance(base, dict) and isinstance(patch, dict):
        out = dict(base)
        for k, v in patch.items():
            out[k] = _strategic_merge(out[k], v) if k in out else v
        return out
    if isinstance(base, list) and isinstance(patch, list):
        if base and patch and all(
            isinstance(x, dict) and "name" in x for x in base + patch
        ):
            merged = {x["name"]: x for x in base}
            order = [x["name"] for x in base]
            for p in patch:
                n = p["name"]
                if n in merged:
                    merged[n] = _strategic_merge(merged[n], p)
                else:
                    order.append(n)
                    merged[n] = p
            return [merged[n] for n in order]
        return patch
    return patch


def _apply_patch(
    docs: List[Dict[str, Any]], patch: Dict[str, Any], base_dir: str
) -> None:
    """kustomize `patches` entry: strategic-merge the patch file onto every
    doc matching the kind/name target (the subset our overlays use)."""
    ppath = os.path.normpath(os.path.join(base_dir, patch["path"]))
    patch_docs = _load_yaml_docs(ppath)
    target = patch.get("target") or {}
    matched = False
    for pdoc in patch_docs:
        t_kind = target.get("kind") or pdoc.get("kind")
        t_name = target.get("name") or pdoc.get("metadata", {}).get("name")
        for i, d in enumerate(docs):
            if d.get("kind") != t_kind:
                continue
            if t_name and d.get("metadata", {}).get("name") != t_name:
                continue
            docs[i] = _strategic_merge(d, pdoc)
            matched = True
    if not matched:
        raise ValueError(f"{ppath}: patch target matched no resource")


def _label_selectors_and_templates(doc: Dict[str, Any], labels: Dict[str, str]):
    """kustomize semantics: commonLabels also land on pod templates and
    selectors of workload kinds."""
    spec = doc.get("spec")
    if not isinstance(spec, dict):
        return
    sel = spec.get("selector")
    if isinstance(sel, dict) and ("matchLabels" in sel or doc.get("kind") in
                                  ("Deployment", "StatefulSet", "DaemonSet")):
        sel["matchLabels"] = {**(sel.get("matchLabels") or {}), **labels}
    elif isinstance(sel, dict) and doc.get("kind") == "Service":
        spec["selector"] = {**sel, **labels}
    tpl = spec.get("template")
    if isinstance(tpl, dict):
        md = tpl.setdefault("metadata", {})
        md["labels"] = {**(md.get("labels") or {}), **labels}


def _split_image_ref(ref: str):
    """'name[:tag][@digest]' -> (name, tag) — the ':' only splits a tag if
    it follows the last '/', so registry ports (localhost:5000/op) and
    digests (op@sha256:...) match by name like real kustomize."""
    base = ref.split("@", 1)[0]
    slash, colon = base.rfind("/"), base.rfind(":")
    if colon > slash:
        return base[:colon], base[colon + 1:]
    return base, None


def _override_image(docs: List[Dict[str, Any]], img: Dict[str, str]) -> None:
    name = img.get("name", "")
    new_name = img.get("newName", name)
    new_tag = img.get("newTag")

    def visit(obj: Any) -> None:
        if isinstance(obj, dict):
            image = obj.get("image")
            if isinstance(image, str) and _split_image_ref(image)[0] == name:
                tag = new_tag or _split_image_ref(image)[1] or "latest"
                obj["image"] = f"{new_name}:{tag}"
            for v in obj.values():
                visit(v)
        elif isinstance(obj, list):
            for v in obj:
                visit(v)

    visit(docs)


def to_yaml_stream(docs: Iterable[Dict[str, Any]]) -> str:
    return "---\n".join(
        yaml.safe_dump(d, sort_keys=False, default_flow_style=False)
        for d in docs
    )


def render_overlay(
    repo_root: str,
    overlay: str = "standalone",
    image: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Render manifests/overlays/{overlay}; optionally retarget the operator
    image (`registry/name:tag`)."""
    docs = render_kustomization(
        os.path.join(repo_root, "manifests", "overlays", overlay)
    )
    if image:
        new_name, _, new_tag = image.partition(":")
        _override_image(docs, {
            "name": "kubeflow/tpu-training-operator",
            "newName": new_name,
            "newTag": new_tag or "latest",
        })
    return docs
