"""ctypes bindings for the native (C++) operator runtime core.

The reference's only native component is its Go operator binary; here the
operator's hot paths — the work queue the reconcile dispatch spins on and
the expectations counters consulted on every sync — are C++
(native/workqueue.cc, native/expectations.cc), built by `make native` into
libtpuoperator.so next to this file.

`make_queue()` / `make_expectations()` return the native implementation
when the library is present (and TPU_OPERATOR_NATIVE != 0), else the pure
Python fallback, behind identical interfaces — callers never branch.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_LIB_NAME = "libtpuoperator.so"
_MAX_KEY = 4096


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("TPU_OPERATOR_NATIVE", "1") == "0":
        return None
    path = os.path.join(os.path.dirname(__file__), _LIB_NAME)
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.wq_new.restype = ctypes.c_void_p
    lib.wq_new.argtypes = [ctypes.c_double, ctypes.c_double]
    lib.wq_free.argtypes = [ctypes.c_void_p]
    lib.wq_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_add_after.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
    lib.wq_add_rate_limited.restype = ctypes.c_double
    lib.wq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_get.restype = ctypes.c_int
    lib.wq_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_double,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.wq_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_forget.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_num_requeues.restype = ctypes.c_int
    lib.wq_num_requeues.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_len.restype = ctypes.c_int
    lib.wq_len.argtypes = [ctypes.c_void_p]
    lib.wq_pending_delayed.restype = ctypes.c_int
    lib.wq_pending_delayed.argtypes = [ctypes.c_void_p]
    lib.wq_empty.restype = ctypes.c_int
    lib.wq_empty.argtypes = [ctypes.c_void_p]
    lib.wq_shutdown.argtypes = [ctypes.c_void_p]
    lib.exp_new.restype = ctypes.c_void_p
    lib.exp_new.argtypes = [ctypes.c_double]
    lib.exp_free.argtypes = [ctypes.c_void_p]
    for fn in ("exp_set", "exp_raise", "exp_lower"):
        getattr(lib, fn).argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_longlong,
        ]
    lib.exp_satisfied.restype = ctypes.c_int
    lib.exp_satisfied.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.exp_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.exp_count.restype = ctypes.c_int
    lib.exp_count.argtypes = [ctypes.c_void_p]
    return lib


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_loaded = False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_loaded
    with _lib_lock:
        if not _lib_loaded:
            _lib = _load()
            _lib_loaded = True
        return _lib


def native_available() -> bool:
    return get_lib() is not None


class _NativeHandle:
    """Lifetime guard for a C++ handle shared with worker threads.

    The hazard (ADVICE r1, medium): __del__ freeing the handle while a
    worker is still blocked inside a native call (OperatorManager.stop()
    joins workers with a timeout, so stragglers outlive the Python object)
    is a use-after-free of the C++ mutex/condvar.  Every native call runs
    inside enter()/exit() which refcounts in-flight calls; close() first
    shuts the native object down (waking blocked getters), then frees only
    when no call is in flight — otherwise the LAST exiting call frees.  A
    call arriving after close() is refused by enter() and the wrapper
    returns its benign default instead of touching freed memory."""

    def __init__(self, lib, handle, free_name: str, shutdown_name: Optional[str]):
        self.lib = lib
        self.h = handle
        self._free = getattr(lib, free_name)
        self._shutdown = getattr(lib, shutdown_name) if shutdown_name else None
        self._lock = threading.Lock()
        self._inflight = 0
        self._closed = False

    def enter(self) -> bool:
        with self._lock:
            if self._closed:
                return False
            self._inflight += 1
            return True

    def exit(self) -> None:
        free_now = None
        with self._lock:
            self._inflight -= 1
            if self._closed and self._inflight == 0 and self.h is not None:
                free_now, self.h = self.h, None
        if free_now is not None:
            self._free(free_now)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            h = self.h
        if h is not None and self._shutdown is not None:
            self._shutdown(h)  # wakes any getter blocked in the native call
        free_now = None
        with self._lock:
            if self._inflight == 0 and self.h is not None:
                free_now, self.h = self.h, None
        if free_now is not None:
            self._free(free_now)
        # else: a call is still in flight; its exit() frees the handle


class NativeRateLimitingQueue:
    """Same contract as k8s.informer.RateLimitingQueue, backed by C++.

    Keys must be str (the operator only ever queues namespace/name keys)
    and shorter than 4 KiB; oversized keys raise ValueError (the native
    queue drops the bad key rather than leaving it at the head)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError(f"{_LIB_NAME} not built (run `make native`)")
        self._hd = _NativeHandle(
            self._lib,
            self._lib.wq_new(base_delay * 1000.0, max_delay * 1000.0),
            "wq_free",
            "wq_shutdown",
        )
        self._shutting_down = False

    def __del__(self):
        hd = getattr(self, "_hd", None)
        if hd is not None:
            hd.close()

    def add(self, item: str) -> None:
        if not self._hd.enter():
            return
        try:
            self._lib.wq_add(self._hd.h, item.encode())
        finally:
            self._hd.exit()

    def add_after(self, item: str, delay: float) -> None:
        if not self._hd.enter():
            return
        try:
            self._lib.wq_add_after(self._hd.h, item.encode(), delay * 1000.0)
        finally:
            self._hd.exit()

    def add_rate_limited(self, item: str) -> float:
        """Returns the applied backoff delay in seconds (the C++ call
        returns it in ms) — same contract as the Python queue."""
        if not self._hd.enter():
            return 0.0
        try:
            return self._lib.wq_add_rate_limited(self._hd.h, item.encode()) / 1000.0
        finally:
            self._hd.exit()

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        if not self._hd.enter():
            return None  # closed queue behaves like a shut-down one
        try:
            timeout_ms = -1.0 if timeout is None else timeout * 1000.0
            # each blocking getter needs its own buffer (get() may run on
            # many worker threads concurrently)
            buf = ctypes.create_string_buffer(_MAX_KEY)
            n = self._lib.wq_get(self._hd.h, timeout_ms, buf, _MAX_KEY)
        finally:
            self._hd.exit()
        if n == -2:
            raise ValueError(f"queued key exceeds {_MAX_KEY - 1} bytes")
        if n < 0:
            return None
        return buf.raw[:n].decode()

    def done(self, item: str) -> None:
        if not self._hd.enter():
            return
        try:
            self._lib.wq_done(self._hd.h, item.encode())
        finally:
            self._hd.exit()

    def forget(self, item: str) -> None:
        if not self._hd.enter():
            return
        try:
            self._lib.wq_forget(self._hd.h, item.encode())
        finally:
            self._hd.exit()

    def num_requeues(self, item: str) -> int:
        if not self._hd.enter():
            return 0
        try:
            return self._lib.wq_num_requeues(self._hd.h, item.encode())
        finally:
            self._hd.exit()

    def __len__(self) -> int:
        if not self._hd.enter():
            return 0
        try:
            return self._lib.wq_len(self._hd.h)
        finally:
            self._hd.exit()

    def pending_delayed(self) -> int:
        if not self._hd.enter():
            return 0
        try:
            return self._lib.wq_pending_delayed(self._hd.h)
        finally:
            self._hd.exit()

    def empty(self) -> bool:
        if not self._hd.enter():
            return True
        try:
            return bool(self._lib.wq_empty(self._hd.h))
        finally:
            self._hd.exit()

    @property
    def shutting_down(self) -> bool:
        return self._shutting_down

    def shut_down(self) -> None:
        self._shutting_down = True
        if not self._hd.enter():
            return
        try:
            self._lib.wq_shutdown(self._hd.h)
        finally:
            self._hd.exit()


class NativeControllerExpectations:
    """Same contract as engine.expectations.ControllerExpectations."""

    def __init__(self, ttl_seconds: float = 300.0):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError(f"{_LIB_NAME} not built (run `make native`)")
        self._hd = _NativeHandle(
            self._lib, self._lib.exp_new(ttl_seconds * 1000.0), "exp_free", None
        )

    def __del__(self):
        hd = getattr(self, "_hd", None)
        if hd is not None:
            hd.close()

    def _call(self, fn_name: str, key: str, *args):
        if not self._hd.enter():
            return None
        try:
            return getattr(self._lib, fn_name)(self._hd.h, key.encode(), *args)
        finally:
            self._hd.exit()

    def set_expectations(self, key: str, add: int, delete: int) -> None:
        self._call("exp_set", key, add, delete)

    def expect_creations(self, key: str, adds: int) -> None:
        self.set_expectations(key, adds, 0)

    def expect_deletions(self, key: str, dels: int) -> None:
        self.set_expectations(key, 0, dels)

    def raise_expectations(self, key: str, add: int, delete: int) -> None:
        self._call("exp_raise", key, add, delete)

    def lower_expectations(self, key: str, add: int, delete: int) -> None:
        self._call("exp_lower", key, add, delete)

    def creation_observed(self, key: str) -> None:
        self._call("exp_lower", key, 1, 0)

    def deletion_observed(self, key: str) -> None:
        self._call("exp_lower", key, 0, 1)

    def satisfied_expectations(self, key: str) -> bool:
        # closed (interpreter teardown): report satisfied so a late reconcile
        # is not wedged behind expectations that can no longer resolve
        result = self._call("exp_satisfied", key)
        return True if result is None else bool(result)

    def delete_expectations(self, key: str) -> None:
        self._call("exp_delete", key)


def make_queue(base_delay: float = 0.005, max_delay: float = 1000.0):
    """Native queue when built, else the Python RateLimitingQueue — with the
    same backoff tuning either way."""
    if native_available():
        return NativeRateLimitingQueue(base_delay=base_delay, max_delay=max_delay)
    from tf_operator_tpu.k8s.informer import (
        ItemExponentialFailureRateLimiter,
        RateLimitingQueue,
    )

    return RateLimitingQueue(
        ItemExponentialFailureRateLimiter(base_delay=base_delay, max_delay=max_delay)
    )


def make_expectations():
    """Native expectations when built, else the Python fallback."""
    if native_available():
        return NativeControllerExpectations()
    from tf_operator_tpu.engine.expectations import ControllerExpectations

    return ControllerExpectations()
