"""ctypes bindings for the native (C++) operator runtime core.

The reference's only native component is its Go operator binary; here the
operator's hot paths — the work queue the reconcile dispatch spins on and
the expectations counters consulted on every sync — are C++
(native/workqueue.cc, native/expectations.cc), built by `make native` into
libtpuoperator.so next to this file.

`make_queue()` / `make_expectations()` return the native implementation
when the library is present (and TPU_OPERATOR_NATIVE != 0), else the pure
Python fallback, behind identical interfaces — callers never branch.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_LIB_NAME = "libtpuoperator.so"
_MAX_KEY = 4096


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("TPU_OPERATOR_NATIVE", "1") == "0":
        return None
    path = os.path.join(os.path.dirname(__file__), _LIB_NAME)
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.wq_new.restype = ctypes.c_void_p
    lib.wq_new.argtypes = [ctypes.c_double, ctypes.c_double]
    lib.wq_free.argtypes = [ctypes.c_void_p]
    lib.wq_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_add_after.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
    lib.wq_add_rate_limited.restype = ctypes.c_double
    lib.wq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_get.restype = ctypes.c_int
    lib.wq_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_double,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.wq_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_forget.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_num_requeues.restype = ctypes.c_int
    lib.wq_num_requeues.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_len.restype = ctypes.c_int
    lib.wq_len.argtypes = [ctypes.c_void_p]
    lib.wq_pending_delayed.restype = ctypes.c_int
    lib.wq_pending_delayed.argtypes = [ctypes.c_void_p]
    lib.wq_empty.restype = ctypes.c_int
    lib.wq_empty.argtypes = [ctypes.c_void_p]
    lib.wq_shutdown.argtypes = [ctypes.c_void_p]
    lib.exp_new.restype = ctypes.c_void_p
    lib.exp_new.argtypes = [ctypes.c_double]
    lib.exp_free.argtypes = [ctypes.c_void_p]
    for fn in ("exp_set", "exp_raise", "exp_lower"):
        getattr(lib, fn).argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_longlong,
        ]
    lib.exp_satisfied.restype = ctypes.c_int
    lib.exp_satisfied.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.exp_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.exp_count.restype = ctypes.c_int
    lib.exp_count.argtypes = [ctypes.c_void_p]
    return lib


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_loaded = False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_loaded
    with _lib_lock:
        if not _lib_loaded:
            _lib = _load()
            _lib_loaded = True
        return _lib


def native_available() -> bool:
    return get_lib() is not None


class NativeRateLimitingQueue:
    """Same contract as k8s.informer.RateLimitingQueue, backed by C++.

    Keys must be str (the operator only ever queues namespace/name keys)
    and shorter than 4 KiB; oversized keys raise ValueError."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError(f"{_LIB_NAME} not built (run `make native`)")
        self._h = self._lib.wq_new(base_delay * 1000.0, max_delay * 1000.0)
        self._shutting_down = False

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.wq_free(h)

    def add(self, item: str) -> None:
        self._lib.wq_add(self._h, item.encode())

    def add_after(self, item: str, delay: float) -> None:
        self._lib.wq_add_after(self._h, item.encode(), delay * 1000.0)

    def add_rate_limited(self, item: str) -> None:
        self._lib.wq_add_rate_limited(self._h, item.encode())

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        timeout_ms = -1.0 if timeout is None else timeout * 1000.0
        # each blocking getter needs its own buffer (get() may run on many
        # worker threads concurrently)
        buf = ctypes.create_string_buffer(_MAX_KEY)
        n = self._lib.wq_get(self._h, timeout_ms, buf, _MAX_KEY)
        if n == -2:
            raise ValueError(f"queued key exceeds {_MAX_KEY - 1} bytes")
        if n < 0:
            return None
        return buf.raw[:n].decode()

    def done(self, item: str) -> None:
        self._lib.wq_done(self._h, item.encode())

    def forget(self, item: str) -> None:
        self._lib.wq_forget(self._h, item.encode())

    def num_requeues(self, item: str) -> int:
        return self._lib.wq_num_requeues(self._h, item.encode())

    def __len__(self) -> int:
        return self._lib.wq_len(self._h)

    def pending_delayed(self) -> int:
        return self._lib.wq_pending_delayed(self._h)

    def empty(self) -> bool:
        return bool(self._lib.wq_empty(self._h))

    @property
    def shutting_down(self) -> bool:
        return self._shutting_down

    def shut_down(self) -> None:
        self._shutting_down = True
        self._lib.wq_shutdown(self._h)


class NativeControllerExpectations:
    """Same contract as engine.expectations.ControllerExpectations."""

    def __init__(self, ttl_seconds: float = 300.0):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError(f"{_LIB_NAME} not built (run `make native`)")
        self._h = self._lib.exp_new(ttl_seconds * 1000.0)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.exp_free(h)

    def set_expectations(self, key: str, add: int, delete: int) -> None:
        self._lib.exp_set(self._h, key.encode(), add, delete)

    def expect_creations(self, key: str, adds: int) -> None:
        self.set_expectations(key, adds, 0)

    def expect_deletions(self, key: str, dels: int) -> None:
        self.set_expectations(key, 0, dels)

    def raise_expectations(self, key: str, add: int, delete: int) -> None:
        self._lib.exp_raise(self._h, key.encode(), add, delete)

    def lower_expectations(self, key: str, add: int, delete: int) -> None:
        self._lib.exp_lower(self._h, key.encode(), add, delete)

    def creation_observed(self, key: str) -> None:
        self._lib.exp_lower(self._h, key.encode(), 1, 0)

    def deletion_observed(self, key: str) -> None:
        self._lib.exp_lower(self._h, key.encode(), 0, 1)

    def satisfied_expectations(self, key: str) -> bool:
        return bool(self._lib.exp_satisfied(self._h, key.encode()))

    def delete_expectations(self, key: str) -> None:
        self._lib.exp_delete(self._h, key.encode())


def make_queue(base_delay: float = 0.005, max_delay: float = 1000.0):
    """Native queue when built, else the Python RateLimitingQueue — with the
    same backoff tuning either way."""
    if native_available():
        return NativeRateLimitingQueue(base_delay=base_delay, max_delay=max_delay)
    from tf_operator_tpu.k8s.informer import (
        ItemExponentialFailureRateLimiter,
        RateLimitingQueue,
    )

    return RateLimitingQueue(
        ItemExponentialFailureRateLimiter(base_delay=base_delay, max_delay=max_delay)
    )


def make_expectations():
    """Native expectations when built, else the Python fallback."""
    if native_available():
        return NativeControllerExpectations()
    from tf_operator_tpu.engine.expectations import ControllerExpectations

    return ControllerExpectations()
