"""TPUREC01 record files + the batch loader over them.

Format (native/dataloader.cc reads the same layout):
  8B magic 'TPUREC01' | u64 record_size | u64 n_records | payload.

A record is the concatenation of fixed-size fields (FieldSpec); a batch of
N records viewed field-wise gives arrays [N, *field.shape] with zero
parsing — one memcpy from the prefetch ring into numpy, then device_put.
"""
from __future__ import annotations

import ctypes
import os
import struct
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"TPUREC01"
HEADER = struct.Struct("<8sQQ")


@dataclass(frozen=True)
class FieldSpec:
    name: str
    shape: Tuple[int, ...]
    dtype: str  # numpy dtype string, e.g. 'uint8', 'int32', 'bfloat16'-free

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, initial=1)) * np.dtype(self.dtype).itemsize


def record_size(fields: Sequence[FieldSpec]) -> int:
    return sum(f.nbytes for f in fields)


def write_records(path: str, fields: Sequence[FieldSpec], columns: Dict[str, np.ndarray]) -> int:
    """Write one record file. `columns[name]` is [N, *shape] for each field;
    all N equal. Returns N."""
    ns = {f.name: len(columns[f.name]) for f in fields}
    n = next(iter(ns.values()))
    if any(v != n for v in ns.values()):
        raise ValueError(f"unequal column lengths: {ns}")
    rsize = record_size(fields)
    with open(path, "wb") as out:
        out.write(HEADER.pack(MAGIC, rsize, n))
        for i in range(n):
            for f in fields:
                # NB: ascontiguousarray promotes 0-d to 1-d; asarray doesn't
                arr = np.asarray(columns[f.name][i], dtype=f.dtype, order="C")
                if arr.shape != tuple(f.shape):
                    raise ValueError(
                        f"{f.name}[{i}]: shape {arr.shape} != spec {f.shape}"
                    )
                out.write(arr.tobytes())
    return n


def read_header(path: str) -> Tuple[int, int]:
    """-> (record_size, n_records); raises on bad magic."""
    with open(path, "rb") as f:
        magic, rsize, n = HEADER.unpack(f.read(HEADER.size))
    if magic != MAGIC:
        raise ValueError(f"{path}: not a TPUREC01 file")
    return rsize, n


_BOUND = set()


def _bind_lib(lib):
    """Declare the dl_* ctypes signatures once per CDLL."""
    if id(lib) not in _BOUND:
        lib.dl_new.restype = ctypes.c_void_p
        lib.dl_new.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.dl_free.argtypes = [ctypes.c_void_p]
        lib.dl_next.restype = ctypes.c_int
        lib.dl_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
        ]
        for fn in ("dl_record_size", "dl_num_records", "dl_batches_produced"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        _BOUND.add(id(lib))
    return lib


def host_sharded_loader(
    paths: Sequence[str],
    fields: Sequence["FieldSpec"],
    batch_size: int,
    info=None,
    **kwargs,
) -> "RecordLoader":
    """RecordLoader wired to THIS host's disjoint input shard from the
    operator-injected env — the tf.data auto-shard / torch
    DistributedSampler analogue for multi-host slices.

    shard_id is the GLOBAL host id (slice-major, the same math
    jax.distributed ranks use — runtime/bootstrap.global_rendezvous) and
    n_shards the global host count, so every host of every slice reads a
    disjoint subset and dp-over-dcn data parallelism sees the full
    dataset exactly once per epoch.  Pass `info` explicitly in tests;
    default reads os.environ (bootstrap.slice_info_from_env)."""
    from tf_operator_tpu.runtime import bootstrap

    if info is None:
        info = bootstrap.slice_info_from_env()
    _, n_shards, shard_id = bootstrap.global_rendezvous(info)
    return RecordLoader(
        paths, fields, batch_size,
        shard_id=shard_id, n_shards=max(1, n_shards), **kwargs,
    )


def host_record_batches(data_dir: str, fields: Sequence["FieldSpec"],
                        batch_size: int, info, map_fn):
    """The examples' on-disk input scaffold in one place: glob the .rec
    shards under `data_dir` (loudly failing on an empty dir), build the
    host-sharded loader EAGERLY — a wrong path or undersized shard must
    fail at startup, not at the first batch when peer hosts are already
    blocked in the gradient all-reduce — print the shard line the smoke
    tests assert on, and yield map_fn(record) batches forever."""
    import glob

    paths = sorted(glob.glob(os.path.join(data_dir, "*.rec")))
    if not paths:
        raise SystemExit(f"no .rec files under {data_dir}")
    loader = host_sharded_loader(paths, fields, batch_size, info=info,
                                 shuffle=True, loop=True)
    print(f"data: records x{loader.num_records()} "
          f"(shard {loader.shard_id}/{loader.n_shards}, "
          f"native={loader.using_native})")

    def batches():
        for rec in loader:
            yield map_fn(rec)

    return batches()


def _split_batch(
    buf: np.ndarray, batch_size: int, fields: Sequence[FieldSpec]
) -> Dict[str, np.ndarray]:
    """View a [batch_size * record_size] byte buffer field-wise (zero copy)."""
    rec = buf.reshape(batch_size, -1)
    out: Dict[str, np.ndarray] = {}
    off = 0
    for f in fields:
        chunk = rec[:, off : off + f.nbytes]
        out[f.name] = chunk.view(f.dtype).reshape((batch_size,) + tuple(f.shape))
        off += f.nbytes
    return out


class RecordLoader:
    """Iterate batches from record files.

    Yields {field: np.ndarray [B, *shape]}. Drop-remainder; seeded per-epoch
    shuffle.  Shard DISJOINTNESS comes from the round-robin record->shard
    assignment (record i belongs to shard i % n_shards), NOT from the
    shuffle: the native path (std::shuffle, implementation-defined
    permutation) and the numpy fallback produce different orders for the
    same seed, and each host only ever permutes its own shard.
    `shard_id`/`n_shards` give each TPU VM host its subset —
    `host_sharded_loader` wires them from the operator-injected env
    (global slice-major host id / total hosts, incl. multislice).
    """

    def __init__(
        self,
        paths: Sequence[str],
        fields: Sequence[FieldSpec],
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        shard_id: int = 0,
        n_shards: int = 1,
        loop: bool = True,
        prefetch_depth: int = 4,
        n_threads: int = 2,
        force_python: bool = False,
    ) -> None:
        if not paths:
            raise ValueError("no record files")
        self.paths = [os.fspath(p) for p in paths]
        self.fields = list(fields)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.shard_id = shard_id
        self.n_shards = n_shards
        self.loop = loop
        self.prefetch_depth = prefetch_depth
        self.n_threads = n_threads

        rsize = record_size(self.fields)
        for p in self.paths:
            got, _ = read_header(p)
            if got != rsize:
                raise ValueError(
                    f"{p}: record_size {got} != field spec total {rsize}"
                )
        self._rsize = rsize

        # a shard smaller than one batch can never produce a full batch
        # (records never repeat within a batch) — fail loudly on both paths,
        # matching dl_new's native-side rejection
        n_mine = self._shard_count()
        if n_mine < batch_size:
            raise ValueError(
                f"shard {shard_id}/{n_shards} holds {n_mine} records "
                f"< batch_size {batch_size}: can never produce a batch"
            )

        self._lib = None
        if not force_python:
            from tf_operator_tpu import native as native_mod

            lib = native_mod.get_lib()
            if lib is not None and hasattr(lib, "dl_new"):
                self._lib = _bind_lib(lib)
                # probe: validate the files through dl_new once, loudly
                self._lib.dl_free(self._new_handle())

    def _new_handle(self):
        """A fresh C++ loader (own prefetch threads + cursor). Each iterator
        owns one — independent streams, no shared state, no use-after-free."""
        h = self._lib.dl_new(
            "\n".join(self.paths).encode(),
            self.batch_size,
            self.prefetch_depth,
            self.n_threads,
            self.shard_id,
            self.n_shards,
            self.seed,
            1 if self.shuffle else 0,
            1 if self.loop else 0,
        )
        if not h:
            raise ValueError("native loader rejected the record files")
        return h

    @property
    def using_native(self) -> bool:
        return self._lib is not None

    def _shard_count(self) -> int:
        total = sum(read_header(p)[1] for p in self.paths)
        return total // self.n_shards + (
            1 if total % self.n_shards > self.shard_id else 0
        )

    def num_records(self) -> int:
        return self._shard_count()

    # ------------------------------------------------------------- iteration
    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        """Every __iter__ is an independent fresh stream from the start,
        on both paths (native: a dedicated C++ loader per iterator)."""
        if self._lib is None:
            return self._iter_python()
        return self._iter_native(self._new_handle())

    def _iter_native(self, handle):
        nbytes = self.batch_size * self._rsize
        try:
            while True:
                buf = np.empty(nbytes, np.uint8)
                rc = self._lib.dl_next(
                    handle,
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    nbytes,
                )
                if rc == 0:
                    return
                if rc < 0:
                    raise IOError("native loader read error")
                yield _split_batch(buf, self.batch_size, self.fields)
        finally:
            self._lib.dl_free(handle)

    def _iter_python(self):
        # same record indexing/shuffle semantics as the native path
        index: List[Tuple[int, int]] = []
        counts = [read_header(p)[1] for p in self.paths]
        g = 0
        for fi, n in enumerate(counts):
            for r in range(n):
                if g % self.n_shards == self.shard_id:
                    index.append((fi, r))
                g += 1
        handles = [open(p, "rb") for p in self.paths]
        try:
            epoch = 0
            while True:
                order = np.arange(len(index))
                if self.shuffle:
                    np.random.default_rng(self.seed + epoch).shuffle(order)
                for s in range(0, len(order) - self.batch_size + 1, self.batch_size):
                    buf = np.empty(self.batch_size * self._rsize, np.uint8)
                    for j, oi in enumerate(order[s : s + self.batch_size]):
                        fi, r = index[oi]
                        handles[fi].seek(HEADER.size + r * self._rsize)
                        chunk = handles[fi].read(self._rsize)
                        buf[j * self._rsize : (j + 1) * self._rsize] = np.frombuffer(
                            chunk, np.uint8
                        )
                    yield _split_batch(buf, self.batch_size, self.fields)
                if not self.loop:
                    return
                epoch += 1
        finally:
            for h in handles:
                h.close()
