"""Text -> pre-tokenized .rec shards: the missing front half of the data
pipeline (data/loader.py consumes fixed-length token rows; this produces
them).

`python -m tf_operator_tpu.data.tokenize --input corpus/*.txt \
    --tokenizer byte --seq-len 2048 --out shards/ --num-shards 8`

Documents are tokenized, joined by EOS, and PACKED into dense [seq_len]
rows (no padding waste — the standard pretraining layout; an LM trained
on packed rows sees document boundaries through the EOS tokens).  Rows
round-robin across shards so every shard is statistically similar and
`host_record_batches`' disjoint per-host assignment stays balanced.

Tokenizers:
  - `byte`: built-in byte-level fallback (vocab exactly 256; NUL doubles
    as the EOS separator — it never occurs in text) — zero dependencies,
    reversible, fits any model vocab >= 256, useful for smokes and
    ablations (this environment has no network egress, so the default
    must not need a download).
  - a PATH to a local Hugging Face tokenizer directory — loaded with
    `transformers.AutoTokenizer.from_pretrained(path,
    local_files_only=True)`, so llama/mistral checkpoints imported with
    models/convert.py train on text tokenized exactly as upstream.

Reference parity: the reference ships no input tooling at all (its
examples generate synthetic data inline, e.g. its dist-mnist estimator
feeds); this is beyond-reference [+] like the rest of the data layer.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Iterable, Iterator, List

import numpy as np

from tf_operator_tpu.data.loader import FieldSpec, write_records


class ByteTokenizer:
    """Reversible byte-level tokenizer: token i is byte i.  NUL (0)
    doubles as EOS — it never occurs in text, so the vocab stays exactly
    256 and fits every model vocab without clamping."""

    vocab_size = 256
    eos_id = 0

    def encode(self, text: str) -> List[int]:
        return [b or 32 for b in text.encode("utf-8")]  # NUL -> space

    def decode(self, ids: Iterable[int]) -> str:
        return bytes(i for i in ids if i > 0).decode("utf-8", "replace")


class HFTokenizer:
    """A local (no-download) Hugging Face tokenizer directory."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self.tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self.tok)
        self.eos_id = self.tok.eos_token_id
        if self.eos_id is None:
            raise SystemExit(
                f"tokenizer at {path} has no eos token — packing needs a "
                f"document separator")

    def encode(self, text: str) -> List[int]:
        return self.tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Iterable[int]) -> str:
        return self.tok.decode(list(ids))


def load_tokenizer(spec: str):
    if spec == "byte":
        return ByteTokenizer()
    if os.path.isdir(spec):
        return HFTokenizer(spec)
    raise SystemExit(
        f"--tokenizer must be 'byte' or a local tokenizer directory, "
        f"got {spec!r} (no-egress environment: remote hub names cannot "
        f"be downloaded)")


def iter_documents(paths: List[str]) -> Iterator[str]:
    """Yield one document per .jsonl line ('text' field) or per
    blank-line-separated block of a .txt file."""
    for p in paths:
        if p.endswith(".jsonl"):
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield json.loads(line)["text"]
        else:
            with open(p) as f:
                block: List[str] = []
                for line in f:
                    if line.strip():
                        block.append(line)
                    elif block:
                        yield "".join(block)
                        block = []
                if block:
                    yield "".join(block)


def pack_rows(docs: Iterator[str], tok, seq_len: int) -> Iterator[np.ndarray]:
    """Greedy-pack `tokenized doc + EOS` streams into dense [seq_len]
    rows; the trailing partial row is dropped (standard pretraining
    packing — a padded tail would teach the model padding)."""
    buf: List[int] = []
    for doc in docs:
        buf.extend(tok.encode(doc))
        buf.append(tok.eos_id)
        while len(buf) >= seq_len:
            yield np.asarray(buf[:seq_len], np.int32)
            del buf[:seq_len]


def write_shards(rows: Iterator[np.ndarray], seq_len: int, out_dir: str,
                 num_shards: int, chunk_rows: int = 4096) -> List[int]:
    """Round-robin rows across `num_shards` logical shards, STREAMING:
    each shard flushes a `tokens-{shard}-{part}.rec` file every
    `chunk_rows` rows, so memory stays O(num_shards x chunk) no matter
    how large the corpus is (a 50GB corpus must not need 200GB of
    resident int32 rows).  Returns per-shard row counts."""
    os.makedirs(out_dir, exist_ok=True)
    fields = [FieldSpec("tokens", (seq_len,), np.int32)]
    buckets: List[List[np.ndarray]] = [[] for _ in range(num_shards)]
    counts = [0] * num_shards
    parts = [0] * num_shards

    def flush(s: int) -> None:
        if not buckets[s]:
            return
        path = os.path.join(out_dir, f"tokens-{s:05d}-{parts[s]:04d}.rec")
        write_records(path, fields, {"tokens": np.stack(buckets[s])})
        counts[s] += len(buckets[s])
        parts[s] += 1
        buckets[s] = []

    for i, row in enumerate(rows):
        s = i % num_shards
        buckets[s].append(row)
        if len(buckets[s]) >= chunk_rows:
            flush(s)
    for s in range(num_shards):
        flush(s)
    return counts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--input", nargs="+", required=True,
                    help=".txt (blank-line-separated docs) or .jsonl "
                         "('text' field) files/globs")
    ap.add_argument("--tokenizer", default="byte",
                    help="'byte' or a local HF tokenizer directory")
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--out", required=True, help="output shard directory")
    ap.add_argument("--num-shards", type=int, default=8,
                    help="shard count (>= the host count that will read)")
    args = ap.parse_args(argv)

    paths: List[str] = []
    for pattern in args.input:
        hits = sorted(glob.glob(pattern))
        if not hits:
            raise SystemExit(f"--input pattern matched nothing: {pattern}")
        paths.extend(hits)
    tok = load_tokenizer(args.tokenizer)
    rows = pack_rows(iter_documents(paths), tok, args.seq_len)
    counts = write_shards(rows, args.seq_len, args.out, args.num_shards)
    total = sum(counts)
    if total == 0:
        raise SystemExit(
            f"no full [{args.seq_len}] rows produced — corpus smaller "
            f"than one sequence?")
    print(f"wrote {total} rows x {args.seq_len} tokens "
          f"(vocab {tok.vocab_size}) across "
          f"{sum(1 for c in counts if c)} shards in {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
