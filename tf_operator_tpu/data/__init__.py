"""Host input pipeline: fixed-record binary files + prefetching loaders.

The reference ships no data path (user containers own it); a TPU framework
must, because the host pipeline feeds the MXU. `write_records` produces the
TPUREC01 format; `RecordLoader` streams batches from it — C++ prefetch
threads (native/dataloader.cc) when the native library is built, a pure
Python reader otherwise, same iterator contract either way.
"""
from tf_operator_tpu.data.loader import (
    FieldSpec,
    RecordLoader,
    read_header,
    write_records,
)

__all__ = ["FieldSpec", "RecordLoader", "read_header", "write_records"]
