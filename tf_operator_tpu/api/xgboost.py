"""XGBoostJob API types, defaults, validation.

Reference parity: pkg/apis/xgboost/v1 + pkg/apis/xgboost/validation.
"""
from __future__ import annotations

from dataclasses import dataclass

from tf_operator_tpu.api import common, job as jobapi

KIND = "XGBoostJob"
PLURAL = "xgboostjobs"

REPLICA_MASTER = "Master"
REPLICA_WORKER = "Worker"
REPLICA_TYPES = [REPLICA_MASTER, REPLICA_WORKER]

# Reference constants.go:22-28
DEFAULT_PORT_NAME = "xgboostjob-port"
DEFAULT_CONTAINER_NAME = "xgboost"
DEFAULT_PORT = 9999
DEFAULT_RESTART_POLICY = common.RESTART_POLICY_NEVER


@dataclass
class XGBoostJob(jobapi.Job):
    kind: str = KIND

    def replica_specs_key(self) -> str:
        return "xgbReplicaSpecs"


def set_defaults(job: XGBoostJob) -> None:
    jobapi.apply_common_defaults(
        job,
        REPLICA_TYPES,
        DEFAULT_CONTAINER_NAME,
        DEFAULT_PORT_NAME,
        DEFAULT_PORT,
        DEFAULT_RESTART_POLICY,
    )


def validate(job: XGBoostJob) -> None:
    """Reference ValidateV1XGBoostJobSpec: valid types, exactly one Master."""
    jobapi.validate_replica_specs(
        job, DEFAULT_CONTAINER_NAME, valid_types=REPLICA_TYPES, kind=KIND
    )
    specs = job.replica_specs or {}
    master = specs.get(REPLICA_MASTER)
    if master is None:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: Master ReplicaSpec must be present"
        )
    if master.replicas is not None and master.replicas != 1:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: There must be only 1 master replica"
        )
