"""PyTorchJob API types, defaults, validation.

Reference parity: pkg/apis/pytorch/v1/{pytorchjob_types.go,defaults.go,
constants.go} + pkg/apis/pytorch/validation/validation.go.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

from tf_operator_tpu.api import common, job as jobapi

KIND = "PyTorchJob"
PLURAL = "pytorchjobs"

REPLICA_MASTER = "Master"
REPLICA_WORKER = "Worker"
REPLICA_TYPES = [REPLICA_MASTER, REPLICA_WORKER]

# Reference constants.go:24-30
DEFAULT_PORT_NAME = "pytorchjob-port"
DEFAULT_CONTAINER_NAME = "pytorch"
DEFAULT_PORT = 23456
DEFAULT_RESTART_POLICY = common.RESTART_POLICY_ON_FAILURE


@dataclass
class PyTorchJob(jobapi.Job):
    kind: str = KIND

    def replica_specs_key(self) -> str:
        return "pytorchReplicaSpecs"


def set_defaults(job: PyTorchJob) -> None:
    """Reference pkg/apis/pytorch/v1/defaults.go:36-58."""
    jobapi.apply_common_defaults(
        job,
        REPLICA_TYPES,
        DEFAULT_CONTAINER_NAME,
        DEFAULT_PORT_NAME,
        DEFAULT_PORT,
        DEFAULT_RESTART_POLICY,
    )


def validate(job: PyTorchJob) -> None:
    """Reference ValidateV1PyTorchJobSpec: valid replica types only, exactly
    one Master replica required (pkg/apis/pytorch/validation/validation.go)."""
    jobapi.validate_replica_specs(
        job, DEFAULT_CONTAINER_NAME, valid_types=REPLICA_TYPES, kind=KIND
    )
    specs = job.replica_specs or {}
    master = specs.get(REPLICA_MASTER)
    if master is None:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: Master ReplicaSpec must be present"
        )
    if master.replicas is not None and master.replicas != 1:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: There must be only 1 master replica"
        )
