"""PyTorchJob API types, defaults, validation.

Reference parity: pkg/apis/pytorch/v1/{pytorchjob_types.go,defaults.go,
constants.go} + pkg/apis/pytorch/validation/validation.go.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from tf_operator_tpu.api import common, job as jobapi

KIND = "PyTorchJob"
PLURAL = "pytorchjobs"

REPLICA_MASTER = "Master"
REPLICA_WORKER = "Worker"
REPLICA_TYPES = [REPLICA_MASTER, REPLICA_WORKER]

# Reference constants.go:24-30
DEFAULT_PORT_NAME = "pytorchjob-port"
DEFAULT_CONTAINER_NAME = "pytorch"
DEFAULT_PORT = 23456
DEFAULT_RESTART_POLICY = common.RESTART_POLICY_ON_FAILURE

# torch elastic rendezvous defaults (modern training-operator
# PyTorchJob.spec.elasticPolicy; absent in the reference snapshot)
DEFAULT_RDZV_BACKEND = "c10d"
DEFAULT_RDZV_PORT = 29400


@dataclass
class ElasticPolicy:
    """Torchrun/torch-elastic knobs. When present, the worker count may
    float between min and max (edit replicas; the engine's index-slice
    diffing scales pods) and the operator injects PET_* rendezvous env
    instead of static MASTER_*/RANK/WORLD_SIZE — torchrun negotiates
    membership itself."""

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    rdzv_backend: str = DEFAULT_RDZV_BACKEND
    rdzv_port: int = DEFAULT_RDZV_PORT
    rdzv_host: Optional[str] = None
    rdzv_id: Optional[str] = None
    n_proc_per_node: Optional[int] = None
    max_restarts: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.min_replicas is not None:
            d["minReplicas"] = self.min_replicas
        if self.max_replicas is not None:
            d["maxReplicas"] = self.max_replicas
        if self.rdzv_backend != DEFAULT_RDZV_BACKEND:
            d["rdzvBackend"] = self.rdzv_backend
        if self.rdzv_port != DEFAULT_RDZV_PORT:
            d["rdzvPort"] = self.rdzv_port
        if self.rdzv_host is not None:
            d["rdzvHost"] = self.rdzv_host
        if self.rdzv_id is not None:
            d["rdzvId"] = self.rdzv_id
        if self.n_proc_per_node is not None:
            d["nProcPerNode"] = self.n_proc_per_node
        if self.max_restarts is not None:
            d["maxRestarts"] = self.max_restarts
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["ElasticPolicy"]:
        if d is None:
            return None
        return cls(
            min_replicas=d.get("minReplicas"),
            max_replicas=d.get("maxReplicas"),
            rdzv_backend=d.get("rdzvBackend", DEFAULT_RDZV_BACKEND),
            rdzv_port=d.get("rdzvPort", DEFAULT_RDZV_PORT),
            rdzv_host=d.get("rdzvHost"),
            rdzv_id=d.get("rdzvId"),
            n_proc_per_node=d.get("nProcPerNode"),
            max_restarts=d.get("maxRestarts"),
        )


@dataclass
class PyTorchJob(jobapi.Job):
    kind: str = KIND
    elastic_policy: Optional[ElasticPolicy] = None

    def replica_specs_key(self) -> str:
        return "pytorchReplicaSpecs"

    def extra_spec_to_dict(self) -> Dict[str, Any]:
        if self.elastic_policy is None:
            return {}
        # {} still round-trips presence (all-default policy)
        return {"elasticPolicy": self.elastic_policy.to_dict()}

    def extra_spec_from_dict(self, spec: Dict[str, Any]) -> None:
        self.elastic_policy = ElasticPolicy.from_dict(spec.get("elasticPolicy"))


def set_defaults(job: PyTorchJob) -> None:
    """Reference pkg/apis/pytorch/v1/defaults.go:36-58 (+ elastic bound
    defaulting: minReplicas -> 1, a CONSTANT — deriving bounds from the
    current replica count would bake different PET_NNODES into pods
    created before and after a scale edit)."""
    jobapi.apply_common_defaults(
        job,
        REPLICA_TYPES,
        DEFAULT_CONTAINER_NAME,
        DEFAULT_PORT_NAME,
        DEFAULT_PORT,
        DEFAULT_RESTART_POLICY,
    )
    if job.elastic_policy is not None and job.elastic_policy.min_replicas is None:
        job.elastic_policy.min_replicas = 1


def validate(job: PyTorchJob) -> None:
    """Reference ValidateV1PyTorchJobSpec: valid replica types only, exactly
    one Master replica required (pkg/apis/pytorch/validation/validation.go).
    With an elasticPolicy (modern semantics) the Master is optional —
    torchrun's rendezvous replaces the static master — and the Worker count
    must sit within [minReplicas, maxReplicas]."""
    jobapi.validate_replica_specs(
        job, DEFAULT_CONTAINER_NAME, valid_types=REPLICA_TYPES, kind=KIND
    )
    specs = job.replica_specs or {}
    master = specs.get(REPLICA_MASTER)
    if job.elastic_policy is not None:
        ep = job.elastic_policy
        if master is not None:
            # a static Master and a floating rendezvous are incoherent: the
            # master pod would join (and overflow) the torchrun group
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: elasticPolicy and a Master "
                f"ReplicaSpec are mutually exclusive (torchrun's rendezvous "
                f"replaces the static master)"
            )
        if ep.max_replicas is None:
            # the bound is baked into every pod's PET_NNODES; without an
            # explicit value it would drift with the replica count
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: elasticPolicy.maxReplicas is "
                f"required"
            )
        if ep.min_replicas is not None and ep.min_replicas > ep.max_replicas:
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: elasticPolicy.minReplicas "
                f"{ep.min_replicas} > maxReplicas {ep.max_replicas}"
            )
        worker = specs.get(REPLICA_WORKER)
        if worker is None:
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: elastic jobs need a Worker "
                f"ReplicaSpec"
            )
        n = worker.replicas if worker.replicas is not None else 1
        if ep.min_replicas is not None and n < ep.min_replicas:
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: Worker replicas {n} < "
                f"elasticPolicy.minReplicas {ep.min_replicas}"
            )
        if n > ep.max_replicas:
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: Worker replicas {n} > "
                f"elasticPolicy.maxReplicas {ep.max_replicas}"
            )
        return
    if master is None:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: Master ReplicaSpec must be present"
        )
    if master.replicas is not None and master.replicas != 1:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: There must be only 1 master replica"
        )
