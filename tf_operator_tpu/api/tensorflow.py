"""TFJob API types, defaults, validation, helpers.

Reference parity: pkg/apis/tensorflow/v1/{types.go,defaults.go,constants.go,
common.go,util.go} + pkg/apis/tensorflow/validation/validation.go.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from tf_operator_tpu.api import common, job as jobapi

KIND = "TFJob"
PLURAL = "tfjobs"

# Replica types (reference types.go:75-94)
REPLICA_PS = "PS"
REPLICA_WORKER = "Worker"
REPLICA_CHIEF = "Chief"
REPLICA_MASTER = "Master"
REPLICA_EVALUATOR = "Evaluator"
REPLICA_TYPES = [
    REPLICA_PS,
    REPLICA_WORKER,
    REPLICA_CHIEF,
    REPLICA_MASTER,
    REPLICA_EVALUATOR,
]

# Defaults (reference constants.go:24-34)
DEFAULT_PORT_NAME = "tfjob-port"
DEFAULT_CONTAINER_NAME = "tensorflow"
DEFAULT_PORT = 2222
DEFAULT_RESTART_POLICY = common.RESTART_POLICY_NEVER

# Success policies (reference common.go:21-22)
SUCCESS_POLICY_DEFAULT = ""  # worker-0 defines success
SUCCESS_POLICY_ALL_WORKERS = "AllWorkers"


def is_chief_or_master(rtype: str) -> bool:
    """Reference util.go:22."""
    return rtype in (REPLICA_CHIEF, REPLICA_MASTER)


def is_worker(rtype: str) -> bool:
    return rtype == REPLICA_WORKER


def is_evaluator(rtype: str) -> bool:
    return rtype == REPLICA_EVALUATOR


@dataclass
class TFJob(jobapi.Job):
    kind: str = KIND
    success_policy: Optional[str] = None  # reference types.go:56-61
    enable_dynamic_worker: bool = False  # reference types.go:62-69

    def replica_specs_key(self) -> str:
        return "tfReplicaSpecs"

    def extra_spec_to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.success_policy is not None:
            d["successPolicy"] = self.success_policy
        if self.enable_dynamic_worker:
            d["enableDynamicWorker"] = True
        return d

    def extra_spec_from_dict(self, spec: Dict[str, Any]) -> None:
        self.success_policy = spec.get("successPolicy")
        self.enable_dynamic_worker = bool(spec.get("enableDynamicWorker", False))


def set_defaults(tfjob: TFJob) -> None:
    """Reference SetDefaults_TFJob (defaults.go:94-115)."""
    if tfjob.success_policy is None:
        tfjob.success_policy = SUCCESS_POLICY_DEFAULT
    jobapi.apply_common_defaults(
        tfjob,
        REPLICA_TYPES,
        DEFAULT_CONTAINER_NAME,
        DEFAULT_PORT_NAME,
        DEFAULT_PORT,
        DEFAULT_RESTART_POLICY,
    )


def validate(tfjob: TFJob) -> None:
    """Reference ValidateV1TFJobSpec (validation.go:27-66)."""
    jobapi.validate_replica_specs(
        tfjob,
        DEFAULT_CONTAINER_NAME,
        masterish_types=[REPLICA_CHIEF, REPLICA_MASTER],
        kind=KIND,
    )


def get_port(tfjob: TFJob) -> int:
    """Look up the tfjob-port on the tensorflow container; default 2222
    (reference util.go:29-42)."""
    from tf_operator_tpu.k8s import objects

    for rspec in (tfjob.replica_specs or {}).values():
        c = objects.find_container(rspec.template, DEFAULT_CONTAINER_NAME)
        if c is not None:
            port = objects.find_port(c, DEFAULT_PORT_NAME)
            if port:
                return port
    return DEFAULT_PORT


def contains_chief_or_master(tfjob: TFJob) -> bool:
    """Reference util.go:45-52."""
    return any(is_chief_or_master(rt) for rt in (tfjob.replica_specs or {}))
