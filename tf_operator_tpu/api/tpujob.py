"""TPUJob — the TPU-native job kind (new; no reference counterpart).

The reference schedules per-pod GPU workers (nvidia.com/gpu + NCCL,
reference examples/v1/distribution_strategy/keras-API/multi_worker_tfjob.yaml).
A TPU slice is different: it is allocated whole, one pod per TPU VM *host*,
`google.com/tpu` chips per host, collectives over ICI — so the job unit is
the slice, replica count is derived from the accelerator topology, and
scheduling must be gang-atomic (SURVEY.md §2.10, §7.4 item 1).

Spec shape:
  spec:
    acceleratorType: "v4-32"          # generation-chips
    topology: "2x2x4"                 # optional chip topology override
    numSlices: 1                      # multislice (DCN-connected) jobs
    tpuReplicaSpecs:
      Worker: {replicas: <derived>, template: {...}}
    runPolicy: {...}
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from tf_operator_tpu.api import common, job as jobapi

KIND = "TPUJob"
PLURAL = "tpujobs"

REPLICA_WORKER = "Worker"
REPLICA_TYPES = [REPLICA_WORKER]

DEFAULT_PORT_NAME = "tpujob-port"
DEFAULT_CONTAINER_NAME = "tpu"
DEFAULT_PORT = 8471  # TPU runtime gRPC port on each TPU VM host
COORDINATOR_PORT_NAME = "coordinator-port"
DEFAULT_COORDINATOR_PORT = 8476  # jax.distributed coordinator
DEFAULT_RESTART_POLICY = common.RESTART_POLICY_EXIT_CODE

TPU_RESOURCE = "google.com/tpu"

# chips per TPU VM host, by generation
CHIPS_PER_HOST = {
    "v2": 4,
    "v3": 4,
    "v4": 4,
    "v5p": 4,
    "v5e": 8,
    "v5litepod": 8,
    "v6e": 8,
}

# For v2-v5p GCP numbers acceleratorType in TensorCores (2 per chip):
# 'v4-32' = 32 cores = 16 chips = 4 hosts. v5e/v6e count chips directly.
CORES_PER_CHIP = {
    "v2": 2,
    "v3": 2,
    "v4": 2,
    "v5p": 2,
    "v5e": 1,
    "v5litepod": 1,
    "v6e": 1,
}

_ACCEL_RE = re.compile(r"^(v\d+(?:p|e|litepod)?)-(\d+)$")


def parse_accelerator_type(accelerator_type: str) -> Tuple[str, int]:
    """'v4-32' -> ('v4', 16 chips): the numeric suffix is TensorCores for
    v2-v5p and chips for v5e/v6e. Raises ValidationError on bad input."""
    m = _ACCEL_RE.match(accelerator_type or "")
    if not m:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: bad acceleratorType {accelerator_type!r} "
            f"(want e.g. 'v4-32')"
        )
    gen, count = m.group(1), int(m.group(2))
    if gen not in CHIPS_PER_HOST:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: unknown TPU generation {gen!r}"
        )
    if count <= 0:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: chip count must be positive"
        )
    chips = max(1, count // CORES_PER_CHIP[gen])
    return gen, chips


def parse_topology(topology: str) -> int:
    """'2x2x4' -> 16 chips. Raises ValidationError on bad input."""
    try:
        dims = [int(d) for d in topology.lower().split("x")]
    except ValueError:
        dims = []
    if not dims or any(d <= 0 for d in dims):
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: bad topology {topology!r} (want e.g. '2x2x4')"
        )
    return math.prod(dims)


def slice_hosts(accelerator_type: str) -> int:
    """Number of TPU VM hosts (= pods) in one slice of `accelerator_type`."""
    gen, chips = parse_accelerator_type(accelerator_type)
    per_host = CHIPS_PER_HOST[gen]
    return max(1, math.ceil(chips / per_host))


def chips_per_host(accelerator_type: str) -> int:
    gen, chips = parse_accelerator_type(accelerator_type)
    return min(chips, CHIPS_PER_HOST[gen])


@dataclass
class TPUJob(jobapi.Job):
    kind: str = KIND
    accelerator_type: str = ""
    topology: Optional[str] = None  # e.g. "2x2x4"
    num_slices: int = 1  # multislice over DCN

    def replica_specs_key(self) -> str:
        return "tpuReplicaSpecs"

    def extra_spec_to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"acceleratorType": self.accelerator_type}
        if self.topology:
            d["topology"] = self.topology
        if self.num_slices != 1:
            d["numSlices"] = self.num_slices
        return d

    def extra_spec_from_dict(self, spec: Dict[str, Any]) -> None:
        self.accelerator_type = spec.get("acceleratorType", "")
        self.topology = spec.get("topology")
        # lenient parse: from_dict runs before validation (engine _sync,
        # webhook), so a malformed value must surface as a ValidationError
        # there, not a ValueError crash-looping the reconcile worker
        self.num_slices = spec.get("numSlices", 1)


def set_defaults(job: TPUJob) -> None:
    """Replicas derive from the slice topology (hosts x numSlices); TPU chips
    are injected as container resources; restart policy defaults to ExitCode
    so preemption (retryable) restarts the slice while user errors fail it."""
    if job.run_policy.clean_pod_policy is None:
        job.run_policy.clean_pod_policy = common.CLEAN_POD_POLICY_RUNNING
    jobapi.set_type_names_to_camel_case(job, REPLICA_TYPES)
    specs = job.replica_specs or {}
    worker = specs.get(REPLICA_WORKER)
    if worker is None:
        return
    try:
        hosts = slice_hosts(job.accelerator_type)
        per_host = chips_per_host(job.accelerator_type)
    except jobapi.ValidationError:
        hosts, per_host = None, None
    if worker.replicas is None and hosts is not None:
        # a malformed numSlices is rejected by validate(); defaults must
        # not crash on it meanwhile
        ns = job.num_slices if jobapi.is_int(job.num_slices) else 1
        worker.replicas = hosts * max(1, ns)
    if not worker.restart_policy:
        worker.restart_policy = DEFAULT_RESTART_POLICY
    jobapi.set_default_port(
        worker.template, DEFAULT_CONTAINER_NAME, DEFAULT_PORT_NAME, DEFAULT_PORT
    )
    jobapi.set_default_port(
        worker.template,
        DEFAULT_CONTAINER_NAME,
        COORDINATOR_PORT_NAME,
        DEFAULT_COORDINATOR_PORT,
    )
    # inject google.com/tpu resource requests/limits on the tpu container
    if per_host is not None:
        from tf_operator_tpu.k8s import objects

        target = objects.default_container(worker.template, DEFAULT_CONTAINER_NAME)
        if target is not None:
            res = target.setdefault("resources", {})
            for kind in ("requests", "limits"):
                res.setdefault(kind, {}).setdefault(TPU_RESOURCE, str(per_host))
    # gang scheduling is mandatory for a slice: minAvailable = all replicas
    sp = job.run_policy.scheduling_policy or common.SchedulingPolicy()
    if sp.min_available is None and worker.replicas is not None:
        sp.min_available = worker.replicas
    job.run_policy.scheduling_policy = sp


def validate(job: TPUJob) -> None:
    jobapi.validate_replica_specs(
        job, DEFAULT_CONTAINER_NAME, valid_types=REPLICA_TYPES, kind=KIND
    )
    gen, chips = parse_accelerator_type(job.accelerator_type)  # raises if bad
    if job.topology is not None and parse_topology(job.topology) != chips:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: topology {job.topology!r} "
            f"({parse_topology(job.topology)} chips) does not match "
            f"acceleratorType {job.accelerator_type!r} ({chips} chips)"
        )
    if not jobapi.is_int(job.num_slices):
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: numSlices must be an integer, "
            f"got {job.num_slices!r}"
        )
    if job.num_slices < 1:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: numSlices must be >= 1"
        )
    worker = (job.replica_specs or {}).get(REPLICA_WORKER)
    if worker is None:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: Worker ReplicaSpec must be present"
        )
    expected = slice_hosts(job.accelerator_type) * max(1, job.num_slices)
    if worker.replicas is not None and worker.replicas != expected:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: replicas ({worker.replicas}) must equal "
            f"hosts-per-slice x numSlices ({expected}) for {job.accelerator_type}"
        )
