from tf_operator_tpu.api import common

__all__ = ["common"]
