"""MXJob API types, defaults, validation.

Reference parity: pkg/apis/mxnet/v1/{mxjob_types.go,defaults.go,constants.go}
+ pkg/apis/mxnet/validation/validation.go.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from tf_operator_tpu.api import common, job as jobapi

KIND = "MXJob"
PLURAL = "mxjobs"

# Replica types (reference mxjob_types.go:58-77)
REPLICA_SCHEDULER = "Scheduler"
REPLICA_SERVER = "Server"
REPLICA_WORKER = "Worker"
REPLICA_TUNER_TRACKER = "TunerTracker"
REPLICA_TUNER_SERVER = "TunerServer"
REPLICA_TUNER = "Tuner"
REPLICA_TYPES = [
    REPLICA_SCHEDULER,
    REPLICA_SERVER,
    REPLICA_WORKER,
    REPLICA_TUNER_TRACKER,
    REPLICA_TUNER_SERVER,
    REPLICA_TUNER,
]

# Job modes (reference mxjob_types.go:46-56)
MODE_TRAIN = "MXTrain"
MODE_TUNE = "MXTune"

# Reference constants.go:8-14
DEFAULT_PORT_NAME = "mxjob-port"
DEFAULT_CONTAINER_NAME = "mxnet"
DEFAULT_PORT = 9091
DEFAULT_RESTART_POLICY = common.RESTART_POLICY_NEVER


def is_scheduler(rtype: str) -> bool:
    return rtype == REPLICA_SCHEDULER


@dataclass
class MXJob(jobapi.Job):
    kind: str = KIND
    job_mode: str = MODE_TRAIN

    def replica_specs_key(self) -> str:
        return "mxReplicaSpecs"

    def extra_spec_to_dict(self) -> Dict[str, Any]:
        return {"jobMode": self.job_mode}

    def extra_spec_from_dict(self, spec: Dict[str, Any]) -> None:
        self.job_mode = spec.get("jobMode", MODE_TRAIN)


def set_defaults(job: MXJob) -> None:
    jobapi.apply_common_defaults(
        job,
        REPLICA_TYPES,
        DEFAULT_CONTAINER_NAME,
        DEFAULT_PORT_NAME,
        DEFAULT_PORT,
        DEFAULT_RESTART_POLICY,
    )


def validate(job: MXJob) -> None:
    """Reference ValidateV1MXJobSpec: <=1 Scheduler
    (pkg/apis/mxnet/validation/validation.go)."""
    jobapi.validate_replica_specs(
        job,
        DEFAULT_CONTAINER_NAME,
        masterish_types=[REPLICA_SCHEDULER],
        kind=KIND,
    )
