"""Common job API types — the equivalent of kubeflow/common pkg/apis/common/v1.

The reference consumes these from the external module github.com/kubeflow/common
v0.3.4 (interface reconstructed in SURVEY.md §2.9 from call sites and the CRD
openAPIV3 schemas in reference manifests/base/kubeflow.org_tfjobs.yaml).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Restart / clean-pod policies (reference swagger.json definitions;
# RestartPolicy incl. the operator-implemented ExitCode — design doc
# reference docs/design/tf_job_design_doc.md:84)
# ---------------------------------------------------------------------------
RESTART_POLICY_ALWAYS = "Always"
RESTART_POLICY_ON_FAILURE = "OnFailure"
RESTART_POLICY_NEVER = "Never"
RESTART_POLICY_EXIT_CODE = "ExitCode"
RESTART_POLICIES = (
    RESTART_POLICY_ALWAYS,
    RESTART_POLICY_ON_FAILURE,
    RESTART_POLICY_NEVER,
    RESTART_POLICY_EXIT_CODE,
)

CLEAN_POD_POLICY_ALL = "All"
CLEAN_POD_POLICY_RUNNING = "Running"
CLEAN_POD_POLICY_NONE = "None"
CLEAN_POD_POLICIES = (
    CLEAN_POD_POLICY_ALL,
    CLEAN_POD_POLICY_RUNNING,
    CLEAN_POD_POLICY_NONE,
)

# Job condition types (reference swagger.json JobConditionType; Suspended
# follows the modern training-operator / batch.v1 Job suspend semantics —
# the reference snapshot predates it)
JOB_CREATED = "Created"
JOB_RUNNING = "Running"
JOB_RESTARTING = "Restarting"
JOB_SUCCEEDED = "Succeeded"
JOB_FAILED = "Failed"
JOB_SUSPENDED = "Suspended"
# the gang is waiting for cluster capacity (engine/scheduler.py): set
# while admission fails, cleared on bind — `tpu-jobs describe` shows WHY
# a job is Pending instead of a blank state (no reference counterpart;
# the reference delegates this visibility to volcano's PodGroup status)
JOB_SCHEDULING = "Scheduling"
# an elastic resize (replica-count delta) is in flight: the controller's
# drain → reshard → resume transition (engine/controller.py).  The
# condition's reason names the current phase (ResizeStarted /
# ResizeAdmitted / ResizeReverted / ResizeCompleted once demoted), and
# deliberately does NOT exclude Running: the gang keeps running at the
# old shape until the drain actually begins, and a half-truthful
# "not Running" would hide that from `tpu-jobs describe`.
JOB_RESIZING = "Resizing"


def is_retryable_exit_code(exit_code: int) -> bool:
    """Exit codes >=128 (signal deaths: SIGKILL, SIGSEGV, preemption class)
    are retryable; 1-127 are permanent user errors. Same convention as
    kubeflow/common util/train.IsRetryableExitCode (reference design doc
    docs/design/tf_job_design_doc.md:84)."""
    return exit_code >= 128


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (reference CRD schema schedulingPolicy block,
    manifests/base/kubeflow.org_tfjobs.yaml:62-82)."""

    min_available: Optional[int] = None
    queue: Optional[str] = None
    min_resources: Optional[Dict[str, str]] = None
    priority_class: Optional[str] = None
    # consumed by the scheduler-plugins (coscheduling) gang backend; the
    # volcano PodGroup API has no such field and ignores it
    schedule_timeout_seconds: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.min_available is not None:
            d["minAvailable"] = self.min_available
        if self.queue is not None:
            d["queue"] = self.queue
        if self.min_resources is not None:
            d["minResources"] = self.min_resources
        if self.priority_class is not None:
            d["priorityClass"] = self.priority_class
        if self.schedule_timeout_seconds is not None:
            d["scheduleTimeoutSeconds"] = self.schedule_timeout_seconds
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["SchedulingPolicy"]:
        if d is None:
            return None
        return cls(
            min_available=d.get("minAvailable"),
            queue=d.get("queue"),
            min_resources=d.get("minResources"),
            priority_class=d.get("priorityClass"),
            schedule_timeout_seconds=d.get("scheduleTimeoutSeconds"),
        )


@dataclass
class RunPolicy:
    """Policies for the job as a whole (reference swagger.json RunPolicy)."""

    clean_pod_policy: Optional[str] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None
    # suspend=true tears the job's pods down and halts reconciliation until
    # resumed (modern training-operator semantics, absent in the reference
    # snapshot); the ActiveDeadlineSeconds clock resets on resume.
    suspend: Optional[bool] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.clean_pod_policy is not None:
            d["cleanPodPolicy"] = self.clean_pod_policy
        if self.ttl_seconds_after_finished is not None:
            d["ttlSecondsAfterFinished"] = self.ttl_seconds_after_finished
        if self.active_deadline_seconds is not None:
            d["activeDeadlineSeconds"] = self.active_deadline_seconds
        if self.backoff_limit is not None:
            d["backoffLimit"] = self.backoff_limit
        if self.scheduling_policy is not None:
            d["schedulingPolicy"] = self.scheduling_policy.to_dict()
        if self.suspend is not None:
            d["suspend"] = self.suspend
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "RunPolicy":
        d = d or {}
        return cls(
            clean_pod_policy=d.get("cleanPodPolicy"),
            ttl_seconds_after_finished=d.get("ttlSecondsAfterFinished"),
            active_deadline_seconds=d.get("activeDeadlineSeconds"),
            backoff_limit=d.get("backoffLimit"),
            scheduling_policy=SchedulingPolicy.from_dict(d.get("schedulingPolicy")),
            suspend=d.get("suspend"),
        )


@dataclass
class ReplicaSpec:
    """One replica group: count + pod template + restart policy
    (reference swagger.json ReplicaSpec)."""

    replicas: Optional[int] = None
    template: Dict[str, Any] = field(default_factory=dict)  # podTemplateSpec dict
    restart_policy: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"template": self.template}
        if self.replicas is not None:
            d["replicas"] = self.replicas
        if self.restart_policy is not None:
            d["restartPolicy"] = self.restart_policy
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaSpec":
        return cls(
            replicas=d.get("replicas"),
            template=copy.deepcopy(d.get("template", {})),
            restart_policy=d.get("restartPolicy"),
        )


@dataclass
class JobCondition:
    type: str = ""
    status: str = "True"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_update_time: str = ""
    last_transition_time: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastUpdateTime": self.last_update_time,
            "lastTransitionTime": self.last_transition_time,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "JobCondition":
        return cls(
            type=d.get("type", ""),
            status=d.get("status", "True"),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=d.get("lastUpdateTime", ""),
            last_transition_time=d.get("lastTransitionTime", ""),
        )


@dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    # Operator-driven restart count (ExitCode delete-for-recreate).  The
    # reference has no such field and its BackoffLimit therefore never trips
    # for ExitCode replicas — kubelet restartCount is 0 on every fresh pod
    # (reference gap, kubeflow/common PastBackoffLimit; VERDICT r1 weak 6).
    # Persisting the counter in status is what lets _past_backoff_limit see
    # restarts that happened in prior reconciles.
    restarts: int = 0
    # label-selector string for this type's pods — the /scale subresource's
    # labelSelectorPath points here so the HPA can find the pods behind the
    # count (upstream training-operator does the same)
    selector: Optional[str] = None
    # when the operator last deleted this type's pod(s) for an ExitCode
    # restart — the crash-loop backoff anchor.  Persisted in status so a
    # restarted controller does not forget it is mid-backoff and hot-loop
    # a flapping replica (engine/controller.py restart backoff).
    last_restart_time: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {"active": self.active, "succeeded": self.succeeded, "failed": self.failed}
        if self.restarts:
            d["restarts"] = self.restarts
        if self.selector:
            d["selector"] = self.selector
        if self.last_restart_time:
            d["lastRestartTime"] = self.last_restart_time
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaStatus":
        return cls(
            active=d.get("active", 0),
            succeeded=d.get("succeeded", 0),
            failed=d.get("failed", 0),
            restarts=d.get("restarts", 0),
            selector=d.get("selector"),
            last_restart_time=d.get("lastRestartTime"),
        )


@dataclass
class JobStatus:
    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[str] = None
    completion_time: Optional[str] = None
    last_reconcile_time: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "conditions": [c.to_dict() for c in self.conditions],
            "replicaStatuses": {k: v.to_dict() for k, v in self.replica_statuses.items()},
        }
        if self.start_time is not None:
            d["startTime"] = self.start_time
        if self.completion_time is not None:
            d["completionTime"] = self.completion_time
        if self.last_reconcile_time is not None:
            d["lastReconcileTime"] = self.last_reconcile_time
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "JobStatus":
        d = d or {}
        return cls(
            conditions=[JobCondition.from_dict(c) for c in d.get("conditions", []) or []],
            replica_statuses={
                k: ReplicaStatus.from_dict(v)
                for k, v in (d.get("replicaStatuses", {}) or {}).items()
            },
            start_time=d.get("startTime"),
            completion_time=d.get("completionTime"),
            last_reconcile_time=d.get("lastReconcileTime"),
        )


# ---------------------------------------------------------------------------
# Condition helpers — the equivalent of kubeflow/common pkg/util
# UpdateJobConditions (used throughout reference status.go)
# ---------------------------------------------------------------------------


def get_condition(status: JobStatus, cond_type: str) -> Optional[JobCondition]:
    for c in status.conditions:
        if c.type == cond_type:
            return c
    return None


def has_condition(status: JobStatus, cond_type: str) -> bool:
    c = get_condition(status, cond_type)
    return c is not None and c.status == "True"


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, JOB_FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, JOB_RUNNING)


def is_suspended(status: JobStatus) -> bool:
    return has_condition(status, JOB_SUSPENDED)


def is_resizing(status: JobStatus) -> bool:
    return has_condition(status, JOB_RESIZING)


def demote_condition(
    status: JobStatus,
    cond_type: str,
    now: str,
    reason: Optional[str] = None,
    message: Optional[str] = None,
) -> None:
    """Flip a True condition to False (optionally restating reason/message),
    bumping both timestamps — the single implementation behind condition
    mutual exclusion and explicit demotions like suspend -> resume."""
    cond = get_condition(status, cond_type)
    if cond is None or cond.status != "True":
        return
    cond.status = "False"
    if reason is not None:
        cond.reason = reason
    if message is not None:
        cond.message = message
    cond.last_update_time = now
    cond.last_transition_time = now


def update_job_conditions(
    status: JobStatus, cond_type: str, reason: str, message: str, now: str
) -> None:
    """Append/refresh a condition; terminal or state-changing conditions clear
    the mutually-exclusive ones (Running vs Restarting vs terminal), matching
    kubeflow/common's filterOutCondition behavior observed in reference
    status transitions (status.go:120-211)."""
    # terminal conditions are sticky: once Succeeded/Failed is True, a later
    # replica-type pass in the same status update must not re-promote
    # Running/Restarting/Suspended (e.g. PS failed -> Failed, then the
    # worker loop sees running workers — the job is still Failed), and must
    # not stack the OTHER terminal on top (PS failed + worker-0 succeeded
    # is a Failed job, not both) — first terminal wins.
    if is_finished(status):
        if cond_type in (JOB_RUNNING, JOB_RESTARTING, JOB_SUSPENDED,
                         JOB_SCHEDULING, JOB_RESIZING):
            return
        if cond_type == JOB_SUCCEEDED and is_failed(status):
            return
        if cond_type == JOB_FAILED and is_succeeded(status):
            return
    new_cond = JobCondition(
        type=cond_type,
        status="True",
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )
    existing = get_condition(status, cond_type)
    if existing is not None:
        if (
            existing.reason == reason
            and existing.message == message
            and existing.status == "True"
        ):
            existing.last_update_time = now
            return
        existing.status = "True"  # re-promote a previously demoted condition
        existing.reason = reason
        existing.message = message
        existing.last_update_time = now
        existing.last_transition_time = now
    else:
        status.conditions.append(new_cond)

    # mutual exclusion: Running <-> Restarting; terminal conditions demote both
    def _demote(t: str) -> None:
        if t != cond_type:
            demote_condition(status, t, now)

    if cond_type == JOB_RUNNING:
        _demote(JOB_RESTARTING)
        _demote(JOB_SUSPENDED)
        # a Running gang is by definition no longer waiting for capacity
        _demote(JOB_SCHEDULING)
    elif cond_type == JOB_RESTARTING:
        _demote(JOB_RUNNING)
    elif cond_type == JOB_SUSPENDED:
        _demote(JOB_RUNNING)
        _demote(JOB_RESTARTING)
        _demote(JOB_SCHEDULING)
        # a suspended job holds no pods: whatever resize was in flight is
        # moot — resume re-detects any spec delta from durable state
        _demote(JOB_RESIZING)
    elif cond_type in (JOB_SUCCEEDED, JOB_FAILED):
        _demote(JOB_RUNNING)
        _demote(JOB_RESTARTING)
        _demote(JOB_SUSPENDED)
        _demote(JOB_SCHEDULING)
        _demote(JOB_RESIZING)
