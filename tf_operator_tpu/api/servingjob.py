"""TPUServingJob — the serving-fleet kind (new; no reference counterpart).

The training kinds model a gang: N replicas that live and die together
(a TPU slice is unusable partially, so admission is atomic and restart
is whole-slice).  A serving fleet is the opposite shape: N *independent*
`serve_loop` replicas behind an occupancy-aware router
(models/router.py), scaled by telemetry (engine/servefleet.py).  A
replica dying affects only the requests routed to it; a replica being
added needs no rendezvous, env rewrite, or reshard — the router simply
starts dispatching to it.  The spec therefore carries no gang knobs:

  spec:
    sliceShape: "v5e-8"            # per-replica slice (warm-pool vocabulary)
    servingReplicaSpecs:
      Replica: {replicas: 2, template: {...}}
    autoscale:                     # optional; absent = fixed fleet
      minReplicas: 1
      maxReplicas: 8
      scaleOutQueueWaitP99S: 2.0   # queue-wait p99 trigger (seconds)
      scaleOutBlockedAdmissions: 4 # admission_blocked_on_memory delta trigger
      scaleInOccupancyFloor: 0.3   # KV-block occupancy floor (used/total)
      maxInflightPerReplica: 8     # router's bounded per-replica admission

Consequences wired through the stack (controllers/serving.py
INDEPENDENT_REPLICAS): no cluster-scheduler gang admission (each replica
is placed alone — warm-pool claims still apply per pod), no PodGroup,
and a replicas edit is a plain FLEET RESIZE, never the elastic
drain → reshard → resume phase machine (there is no cross-replica state
to reshard; scale-in drains through the router instead,
docs/serving.md "Serving fleet").
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from tf_operator_tpu.api import common, job as jobapi

KIND = "TPUServingJob"
PLURAL = "tpuservingjobs"

REPLICA_REPLICA = "Replica"
REPLICA_TYPES = [REPLICA_REPLICA]

DEFAULT_CONTAINER_NAME = "serve"
DEFAULT_PORT_NAME = "servingjob-port"
DEFAULT_PORT = 8000  # the replica's inference HTTP port
# replicas default to ExitCode: a preempted/killed replica (>=128) is
# replaced, a crashing model server (1-127) is a permanent failure
DEFAULT_RESTART_POLICY = common.RESTART_POLICY_EXIT_CODE

DEFAULT_SLICE_SHAPE = "v5e-1"
# same vocabulary the warm pool routes standbys on (engine/warmpool.py)
_SHAPE_RE = re.compile(r"^v\d+(?:p|e|litepod)?-\d+$")

# the annotation the warm pool and scheduler read the shape from; set_defaults
# stamps it onto the replica template so fleet pods are warm-pool-claimable
SHAPE_ANNOTATION = "kubeflow.org/slice-shape"


@dataclass
class AutoscaleSpec:
    """Telemetry-driven fleet autoscaling bounds + triggers.  The trigger
    metrics are exactly the serving families PR 2/PR 9 already export:
    queue-wait p99 and admission_blocked_on_memory_total say "requests
    are waiting on capacity" (scale out), KV-block occupancy says "the
    fleet is paying for memory nobody uses" (scale in)."""

    min_replicas: int = 1
    max_replicas: int = 8
    scale_out_queue_wait_p99_s: float = 2.0
    scale_out_blocked_admissions: int = 4
    scale_in_occupancy_floor: float = 0.3
    max_inflight_per_replica: int = 8

    def to_dict(self) -> Dict[str, Any]:
        return {
            "minReplicas": self.min_replicas,
            "maxReplicas": self.max_replicas,
            "scaleOutQueueWaitP99S": self.scale_out_queue_wait_p99_s,
            "scaleOutBlockedAdmissions": self.scale_out_blocked_admissions,
            "scaleInOccupancyFloor": self.scale_in_occupancy_floor,
            "maxInflightPerReplica": self.max_inflight_per_replica,
        }

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["AutoscaleSpec"]:
        if d is None:
            return None
        out = cls()
        if "minReplicas" in d:
            out.min_replicas = d["minReplicas"]
        if "maxReplicas" in d:
            out.max_replicas = d["maxReplicas"]
        if "scaleOutQueueWaitP99S" in d:
            out.scale_out_queue_wait_p99_s = d["scaleOutQueueWaitP99S"]
        if "scaleOutBlockedAdmissions" in d:
            out.scale_out_blocked_admissions = d["scaleOutBlockedAdmissions"]
        if "scaleInOccupancyFloor" in d:
            out.scale_in_occupancy_floor = d["scaleInOccupancyFloor"]
        if "maxInflightPerReplica" in d:
            out.max_inflight_per_replica = d["maxInflightPerReplica"]
        return out


@dataclass
class SLOSpec:
    """Per-job serving SLO targets for the request flight recorder's
    burn-rate engine (engine/reqtrace.py).  Each latency axis carries a
    p99 target in seconds (absent = that axis is not tracked); the
    engine evaluates bad-sample fractions over TWO sliding windows
    (fast + slow, the classic multi-window burn-rate alerting shape:
    the fast window catches a fresh regression, the slow window keeps a
    single slow request from paging) against the error budget
    `1 - objective`, and fires an `slo_burn` DECISION when BOTH exceed
    `burn_threshold`.

      spec:
        slo:
          ttftP99S: 4.0          # time-to-first-token p99 target
          tpotP99S: 0.08         # time-per-output-token p99 target
          queueWaitP99S: 2.0     # submit -> admission p99 target
          e2eP99S: 20.0          # submit -> finish p99 target
          objective: 0.99        # SLO objective (error budget = 1%)
          fastWindowS: 60.0
          slowWindowS: 300.0
          burnThreshold: 1.0     # burn rate that pages (both windows)
    """

    ttft_p99_s: Optional[float] = None
    tpot_p99_s: Optional[float] = None
    queue_wait_p99_s: Optional[float] = None
    e2e_p99_s: Optional[float] = None
    objective: float = 0.99
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 1.0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "objective": self.objective,
            "fastWindowS": self.fast_window_s,
            "slowWindowS": self.slow_window_s,
            "burnThreshold": self.burn_threshold,
        }
        if self.ttft_p99_s is not None:
            d["ttftP99S"] = self.ttft_p99_s
        if self.tpot_p99_s is not None:
            d["tpotP99S"] = self.tpot_p99_s
        if self.queue_wait_p99_s is not None:
            d["queueWaitP99S"] = self.queue_wait_p99_s
        if self.e2e_p99_s is not None:
            d["e2eP99S"] = self.e2e_p99_s
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["SLOSpec"]:
        if d is None:
            return None
        out = cls()
        if "ttftP99S" in d:
            out.ttft_p99_s = d["ttftP99S"]
        if "tpotP99S" in d:
            out.tpot_p99_s = d["tpotP99S"]
        if "queueWaitP99S" in d:
            out.queue_wait_p99_s = d["queueWaitP99S"]
        if "e2eP99S" in d:
            out.e2e_p99_s = d["e2eP99S"]
        if "objective" in d:
            out.objective = d["objective"]
        if "fastWindowS" in d:
            out.fast_window_s = d["fastWindowS"]
        if "slowWindowS" in d:
            out.slow_window_s = d["slowWindowS"]
        if "burnThreshold" in d:
            out.burn_threshold = d["burnThreshold"]
        return out


@dataclass
class TPUServingJob(jobapi.Job):
    kind: str = KIND
    slice_shape: str = DEFAULT_SLICE_SHAPE
    autoscale: Optional[AutoscaleSpec] = None
    slo: Optional[SLOSpec] = None

    def replica_specs_key(self) -> str:
        return "servingReplicaSpecs"

    def extra_spec_to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"sliceShape": self.slice_shape}
        if self.autoscale is not None:
            d["autoscale"] = self.autoscale.to_dict()
        if self.slo is not None:
            d["slo"] = self.slo.to_dict()
        return d

    def extra_spec_from_dict(self, spec: Dict[str, Any]) -> None:
        self.slice_shape = spec.get("sliceShape", DEFAULT_SLICE_SHAPE)
        self.autoscale = AutoscaleSpec.from_dict(spec.get("autoscale"))
        self.slo = SLOSpec.from_dict(spec.get("slo"))


def set_defaults(job: TPUServingJob) -> None:
    """replicas -> 1, restartPolicy -> ExitCode, inference port, and the
    slice-shape annotation stamped onto the template so the warm pool
    (engine/warmpool.py) and scheduler read the fleet's per-replica shape
    from the same place they read every other kind's."""
    jobapi.apply_common_defaults(
        job, REPLICA_TYPES, DEFAULT_CONTAINER_NAME, DEFAULT_PORT_NAME,
        DEFAULT_PORT, DEFAULT_RESTART_POLICY,
    )
    if not job.slice_shape:
        job.slice_shape = DEFAULT_SLICE_SHAPE
    spec = (job.replica_specs or {}).get(REPLICA_REPLICA)
    if spec is not None and isinstance(spec.template, dict):
        meta = spec.template.setdefault("metadata", {})
        meta.setdefault("annotations", {}).setdefault(
            SHAPE_ANNOTATION, job.slice_shape
        )


def validate(job: TPUServingJob) -> None:
    jobapi.validate_replica_specs(
        job, DEFAULT_CONTAINER_NAME, valid_types=REPLICA_TYPES, kind=KIND
    )
    if not _SHAPE_RE.match(job.slice_shape or ""):
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: bad sliceShape {job.slice_shape!r} "
            f"(want e.g. 'v5e-8')"
        )
    _validate_slo(job.slo)
    a = job.autoscale
    if a is None:
        return
    for name, value in (
        ("autoscale.minReplicas", a.min_replicas),
        ("autoscale.maxReplicas", a.max_replicas),
        ("autoscale.scaleOutBlockedAdmissions", a.scale_out_blocked_admissions),
        ("autoscale.maxInflightPerReplica", a.max_inflight_per_replica),
    ):
        if not jobapi.is_int(value):
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: {name} must be an integer, "
                f"got {value!r}"
            )
    if a.min_replicas < 1:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: autoscale.minReplicas must be >= 1 "
            f"(a serving fleet scaled to zero serves nobody; delete or "
            f"suspend the job instead)"
        )
    if a.max_replicas < a.min_replicas:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: autoscale.maxReplicas "
            f"({a.max_replicas}) must be >= minReplicas ({a.min_replicas})"
        )
    if a.max_inflight_per_replica < 1:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: autoscale.maxInflightPerReplica "
            f"must be >= 1"
        )
    if not (
        isinstance(a.scale_out_queue_wait_p99_s, (int, float))
        and a.scale_out_queue_wait_p99_s > 0
    ):
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: autoscale.scaleOutQueueWaitP99S "
            f"must be > 0, got {a.scale_out_queue_wait_p99_s!r}"
        )
    if not (
        isinstance(a.scale_in_occupancy_floor, (int, float))
        and 0.0 <= a.scale_in_occupancy_floor < 1.0
    ):
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: autoscale.scaleInOccupancyFloor "
            f"must be in [0, 1), got {a.scale_in_occupancy_floor!r}"
        )
    if a.scale_out_blocked_admissions < 1:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: autoscale.scaleOutBlockedAdmissions "
            f"must be >= 1"
        )


def _validate_slo(s: Optional[SLOSpec]) -> None:
    if s is None:
        return
    for name, value in (
        ("slo.ttftP99S", s.ttft_p99_s),
        ("slo.tpotP99S", s.tpot_p99_s),
        ("slo.queueWaitP99S", s.queue_wait_p99_s),
        ("slo.e2eP99S", s.e2e_p99_s),
    ):
        if value is None:
            continue
        if not (isinstance(value, (int, float)) and value > 0):
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: {name} must be > 0, "
                f"got {value!r}"
            )
    if not (
        isinstance(s.objective, (int, float)) and 0.0 < s.objective < 1.0
    ):
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: slo.objective must be in (0, 1), "
            f"got {s.objective!r} (1.0 leaves no error budget to burn)"
        )
    for name, value in (
        ("slo.fastWindowS", s.fast_window_s),
        ("slo.slowWindowS", s.slow_window_s),
        ("slo.burnThreshold", s.burn_threshold),
    ):
        if not (isinstance(value, (int, float)) and value > 0):
            raise jobapi.ValidationError(
                f"{KIND}Spec is not valid: {name} must be > 0, "
                f"got {value!r}"
            )
    if s.fast_window_s >= s.slow_window_s:
        raise jobapi.ValidationError(
            f"{KIND}Spec is not valid: slo.fastWindowS "
            f"({s.fast_window_s}) must be < slowWindowS "
            f"({s.slow_window_s}) — multi-window burn alerting needs a "
            f"short window inside a long one"
        )
