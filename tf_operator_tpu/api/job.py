"""Generic Job CR model + shared defaulting machinery.

Framework API modules (tensorflow.py, pytorch.py, mxnet.py, xgboost.py,
tpujob.py) specialize this with their replica types, container names, default
ports, and validation rules — mirroring the per-framework pkg/apis/*/v1
packages of the reference.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api import common
from tf_operator_tpu.k8s import objects


class ValidationError(Exception):
    """Raised when a job spec fails validation (reference
    pkg/apis/tensorflow/validation/validation.go:27)."""


@dataclass
class Job:
    """A training job CR. `replica_specs` maps ReplicaType -> ReplicaSpec.

    Serialized form matches the reference CRD shape:
      {apiVersion, kind, metadata, spec: {<kind>ReplicaSpecs, runPolicy, ...},
       status: {...}}
    """

    kind: str = "Job"
    metadata: Dict[str, Any] = field(default_factory=dict)
    replica_specs: Dict[str, common.ReplicaSpec] = field(default_factory=dict)
    run_policy: common.RunPolicy = field(default_factory=common.RunPolicy)
    status: common.JobStatus = field(default_factory=common.JobStatus)
    api_version: str = objects.API_VERSION

    # ---- identity helpers -------------------------------------------------
    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @property
    def namespace(self) -> str:
        return self.metadata.get("namespace", "default")

    @property
    def uid(self) -> str:
        return self.metadata.get("uid", "")

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    # ---- spec serialization ----------------------------------------------
    def replica_specs_key(self) -> str:
        """Key under .spec holding the replica map, e.g. 'tfReplicaSpecs'."""
        return "replicaSpecs"

    def extra_spec_to_dict(self) -> Dict[str, Any]:
        """Framework-specific extra spec fields (successPolicy, jobMode, ...)."""
        return {}

    def extra_spec_from_dict(self, spec: Dict[str, Any]) -> None:
        pass

    def to_dict(self) -> Dict[str, Any]:
        # replica_specs may be None (spec missing its replica map); serialize
        # as {} so status write-backs of invalid jobs don't crash — the None
        # sentinel is preserved in memory for validate() to reject.
        spec: Dict[str, Any] = {
            self.replica_specs_key(): {
                rt: rs.to_dict() for rt, rs in (self.replica_specs or {}).items()
            },
        }
        run_policy = self.run_policy.to_dict()
        if run_policy:
            spec["runPolicy"] = run_policy
        spec.update(self.extra_spec_to_dict())
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": copy.deepcopy(self.metadata),
            "spec": spec,
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Job":
        job = cls()
        job.api_version = d.get("apiVersion", objects.API_VERSION)
        if d.get("kind"):
            job.kind = d["kind"]
        job.metadata = copy.deepcopy(d.get("metadata", {}) or {})
        spec = d.get("spec", {}) or {}
        replicas = spec.get(job.replica_specs_key())
        if replicas is None:
            job.replica_specs = None  # preserved so validation can reject it
        else:
            job.replica_specs = {
                rt: common.ReplicaSpec.from_dict(rs) for rt, rs in replicas.items()
            }
        job.run_policy = common.RunPolicy.from_dict(spec.get("runPolicy"))
        job.extra_spec_from_dict(spec)
        job.status = common.JobStatus.from_dict(d.get("status"))
        return job


# ---------------------------------------------------------------------------
# Shared defaulting helpers (reference pkg/apis/tensorflow/v1/defaults.go:38-91,
# replicated per framework in the reference)
# ---------------------------------------------------------------------------


def set_type_names_to_camel_case(job: Job, canonical_types: List[str]) -> None:
    """Normalize replica-type keys to canonical case ('ps'->'PS',
    'WORKER'->'Worker') — reference defaults.go:72-91."""
    if not job.replica_specs:
        return
    for canon in canonical_types:
        if canon in job.replica_specs:
            continue  # never overwrite an existing canonical entry
        for existing in list(job.replica_specs.keys()):
            if existing.lower() == canon.lower() and existing != canon:
                job.replica_specs[canon] = job.replica_specs.pop(existing)
                break


def set_default_replicas(
    spec: common.ReplicaSpec, default_restart_policy: str
) -> None:
    """replicas -> 1, restartPolicy -> framework default
    (reference defaults.go:62-69)."""
    if spec.replicas is None:
        spec.replicas = 1
    if not spec.restart_policy:
        spec.restart_policy = default_restart_policy


def set_default_port(
    template: Dict[str, Any], container_name: str, port_name: str, port: int
) -> None:
    """Inject the default RPC port into the framework container if the named
    port is absent. Falls back to container index 0 when no container carries
    the framework name — same as reference defaults.go:38-60."""
    template.setdefault("spec", {}).setdefault("containers", [])
    target = objects.default_container(template, container_name)
    if target is None:
        return
    for p in target.get("ports", []) or []:
        if p.get("name") == port_name:
            return
    target.setdefault("ports", []).append(
        {"name": port_name, "containerPort": port}
    )


def apply_common_defaults(
    job: Job,
    canonical_types: List[str],
    container_name: str,
    port_name: str,
    port: int,
    default_restart_policy: str,
) -> None:
    if job.run_policy.clean_pod_policy is None:
        job.run_policy.clean_pod_policy = common.CLEAN_POD_POLICY_RUNNING
    set_type_names_to_camel_case(job, canonical_types)
    for spec in (job.replica_specs or {}).values():
        set_default_replicas(spec, default_restart_policy)
        set_default_port(spec.template, container_name, port_name, port)


def is_int(value) -> bool:
    """True for a real integer (bools are ints in Python but not in CRD
    schemas) — the single integer predicate for validation and defaulting."""
    return isinstance(value, int) and not isinstance(value, bool)


def _require_nonneg_int(kind: str, field_name: str, value) -> None:
    """Shared numeric-field guard: None passes; anything except a
    non-negative int (the CRD schemas say type: integer, minimum: 0) is a
    ValidationError — never a TypeError crashing the reconcile loop."""
    if value is None:
        return
    if not is_int(value):
        raise ValidationError(
            f"{kind}Spec is not valid: {field_name} must be an integer, "
            f"got {value!r}"
        )
    if value < 0:
        raise ValidationError(
            f"{kind}Spec is not valid: {field_name} must be >= 0, got {value}"
        )


def validate_run_policy(job: Job, kind: str = "Job") -> None:
    """Mirror the CRD schema's RunPolicy constraints (enums + minimums) so
    in-process and webhook validation agree with admission-time schema
    checks even when the CRDs aren't enforcing (FakeCluster, run-local).

    Deliberate ratchet: this also runs at reconcile time, so a CR admitted
    with a negative value before the schema minimums existed fails loudly
    on the next sync instead of acting on the nonsense value (negative ADS/
    backoffLimit already failed jobs instantly; negative TTL would delete
    the CR the moment it finished)."""
    rp = job.run_policy
    if (
        rp.clean_pod_policy is not None
        and rp.clean_pod_policy not in common.CLEAN_POD_POLICIES
    ):
        raise ValidationError(
            f"{kind}Spec is not valid: unknown cleanPodPolicy "
            f"{rp.clean_pod_policy!r}"
        )
    for field_name, value in (
        ("ttlSecondsAfterFinished", rp.ttl_seconds_after_finished),
        ("activeDeadlineSeconds", rp.active_deadline_seconds),
        ("backoffLimit", rp.backoff_limit),
    ):
        _require_nonneg_int(kind, field_name, value)
    sp = rp.scheduling_policy
    if sp is not None:
        _require_nonneg_int(kind, "schedulingPolicy.scheduleTimeoutSeconds",
                            sp.schedule_timeout_seconds)
    if sp is not None and sp.min_available is not None:
        ma = sp.min_available
        _require_nonneg_int(kind, "schedulingPolicy.minAvailable", ma)
        specs = [s for s in (job.replica_specs or {}).values() if s is not None]
        # only cross-check when every count is known — an underivable
        # replicas (e.g. bad acceleratorType left it None) must surface its
        # OWN error, not a misleading 'exceeds total replicas 0'
        if all(is_int(s.replicas) for s in specs):
            total = sum(s.replicas for s in specs)
            if ma > total:
                # a PodGroup with minMember > member count can never
                # schedule: the job would hang Pending forever, silently
                raise ValidationError(
                    f"{kind}Spec is not valid: schedulingPolicy.minAvailable "
                    f"{ma} exceeds total replicas {total}"
                )


def validate_replica_specs(
    job: Job,
    container_name: str,
    valid_types: Optional[List[str]] = None,
    masterish_types: Optional[List[str]] = None,
    kind: str = "Job",
) -> None:
    """Shared validation (reference validation.go:27-66): specs non-nil,
    containers present, image set, >=1 container with the framework name,
    <=1 chief/master replica."""
    specs = job.replica_specs
    if specs is None or not isinstance(specs, dict):
        raise ValidationError(f"{kind}Spec is not valid")
    found_masterish = 0
    for rtype, rspec in specs.items():
        if valid_types is not None and rtype not in valid_types:
            raise ValidationError(
                f"{kind}Spec is not valid: unknown replica type {rtype!r}"
            )
        if rspec is not None:
            # the CRD schema enforces type/minimum at admission; mirror it
            # here so in-process/webhook paths agree (a negative count
            # would read as "delete every pod" to the engine)
            _require_nonneg_int(kind, f"{rtype} replicas", rspec.replicas)
        if (
            rspec is not None
            and rspec.restart_policy is not None
            and rspec.restart_policy not in common.RESTART_POLICIES
        ):
            raise ValidationError(
                f"{kind}Spec is not valid: unknown restartPolicy "
                f"{rspec.restart_policy!r} for {rtype}"
            )
        containers = (
            (rspec.template or {}).get("spec", {}).get("containers", []) or []
            if rspec is not None
            else []
        )
        if rspec is None or not containers:
            raise ValidationError(
                f"{kind}Spec is not valid: containers definition expected in {rtype}"
            )
        if masterish_types and rtype in masterish_types:
            found_masterish += 1
        num_named = 0
        for c in containers:
            if not c.get("image"):
                raise ValidationError(
                    f"{kind}Spec is not valid: Image is undefined in the container of {rtype}"
                )
            if c.get("name") == container_name:
                num_named += 1
        if num_named == 0:
            raise ValidationError(
                f"{kind}Spec is not valid: There is no container named "
                f"{container_name} in {rtype}"
            )
    if found_masterish > 1:
        raise ValidationError(
            f"{kind}Spec is not valid: more than 1 chief/master found"
        )
    # after the per-spec checks so minAvailable-vs-total sums validated
    # replica counts (a bad replicas value gets its clearer error first)
    validate_run_policy(job, kind)
