"""XGBoostJob controller adapter — Rabit/LightGBM env + master-gated status.

Reference parity: pkg/controller.v1/xgboost/{xgboost.go,xgboostjob_controller.go}.
Env (xgboost.go:18-100): MASTER_ADDR/PORT, WORLD_SIZE, RANK (worker rank
offset by master count), PYTHONUNBUFFERED; LightGBM WORKER_PORT/WORKER_ADDRS
when distributed.
"""
from __future__ import annotations

from typing import Any, Dict

from tf_operator_tpu.api import common
from tf_operator_tpu.api import xgboost as xgbapi
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.controller import JobEngine
from tf_operator_tpu.controllers.shared_status import master_based_update_job_status
from tf_operator_tpu.k8s import objects


def get_port(job: xgbapi.XGBoostJob, rtype: str) -> int:
    spec = (job.replica_specs or {}).get(rtype)
    if spec is None:
        return xgbapi.DEFAULT_PORT
    return objects.replica_port(
        spec.template, xgbapi.DEFAULT_CONTAINER_NAME,
        xgbapi.DEFAULT_PORT_NAME, xgbapi.DEFAULT_PORT,
    )


def total_replicas(job: xgbapi.XGBoostJob) -> int:
    return sum(s.replicas or 0 for s in (job.replica_specs or {}).values())


class XGBoostAdapter(FrameworkAdapter):
    KIND = xgbapi.KIND
    PLURAL = xgbapi.PLURAL
    REPLICA_TYPES = xgbapi.REPLICA_TYPES
    CONTAINER_NAME = xgbapi.DEFAULT_CONTAINER_NAME
    PORT_NAME = xgbapi.DEFAULT_PORT_NAME
    DEFAULT_PORT = xgbapi.DEFAULT_PORT

    def from_dict(self, d: Dict[str, Any]) -> xgbapi.XGBoostJob:
        return xgbapi.XGBoostJob.from_dict(d)

    def set_defaults(self, job: xgbapi.XGBoostJob) -> None:
        xgbapi.set_defaults(job)

    def validate(self, job: xgbapi.XGBoostJob) -> None:
        xgbapi.validate(job)

    def set_cluster_spec(
        self, job: xgbapi.XGBoostJob, pod_template: Dict[str, Any], rtype: str, index: int
    ) -> None:
        rank = index
        specs = job.replica_specs or {}
        if rtype == xgbapi.REPLICA_WORKER:
            master = specs.get(xgbapi.REPLICA_MASTER)
            rank += (master.replicas or 0) if master else 0
        total = total_replicas(job)
        env = {
            "MASTER_PORT": str(get_port(job, xgbapi.REPLICA_MASTER)),
            "MASTER_ADDR": JobEngine.gen_general_name(
                job.name, xgbapi.REPLICA_MASTER, 0
            ),
            "WORLD_SIZE": str(total),
            "RANK": str(rank),
            "PYTHONUNBUFFERED": "0",
        }
        if total > 1:
            worker_port = get_port(job, xgbapi.REPLICA_WORKER)
            env["WORKER_PORT"] = str(worker_port)
            env["WORKER_ADDRS"] = ",".join(
                JobEngine.gen_general_name(job.name, xgbapi.REPLICA_WORKER, i)
                for i in range(total - 1)
            )
        for c in pod_template.get("spec", {}).get("containers", []) or []:
            for k, v in env.items():
                objects.set_env(c, k, v)

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        return rtype == xgbapi.REPLICA_MASTER

    def update_job_status(self, engine, job, ctx: StatusContext) -> None:
        with engine.tracer.span("XGBoostJob.status_rules"):
            master_based_update_job_status(
                self.KIND, job, ctx, master_type=xgbapi.REPLICA_MASTER
            )
