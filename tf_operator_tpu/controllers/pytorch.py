"""PyTorchJob controller adapter — MASTER_ADDR/RANK env + master-gated status.

Reference parity: pkg/controller.v1/pytorch/{pytorch.go,pytorchjob_controller.go}.
Env injection SetPodEnv (pytorch.go:13-68): MASTER_ADDR is the master-0
service name ('localhost' on the master itself), RANK is worker index+1,
WORLD_SIZE is the replica sum — applied to ALL containers.
"""
from __future__ import annotations

from typing import Any, Dict

from tf_operator_tpu.api import common
from tf_operator_tpu.api import pytorch as ptapi
from tf_operator_tpu.api.job import ValidationError
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.controller import JobEngine
from tf_operator_tpu.controllers.shared_status import master_based_update_job_status
from tf_operator_tpu.k8s import objects


def total_replicas(job: ptapi.PyTorchJob) -> int:
    return sum(s.replicas or 0 for s in (job.replica_specs or {}).values())


def master_port(job: ptapi.PyTorchJob) -> int:
    spec = (job.replica_specs or {}).get(ptapi.REPLICA_MASTER)
    if spec is None:
        return ptapi.DEFAULT_PORT
    return objects.replica_port(
        spec.template, ptapi.DEFAULT_CONTAINER_NAME,
        ptapi.DEFAULT_PORT_NAME, ptapi.DEFAULT_PORT,
    )


class PyTorchAdapter(FrameworkAdapter):
    KIND = ptapi.KIND
    PLURAL = ptapi.PLURAL
    REPLICA_TYPES = ptapi.REPLICA_TYPES
    CONTAINER_NAME = ptapi.DEFAULT_CONTAINER_NAME
    PORT_NAME = ptapi.DEFAULT_PORT_NAME
    DEFAULT_PORT = ptapi.DEFAULT_PORT

    def from_dict(self, d: Dict[str, Any]) -> ptapi.PyTorchJob:
        return ptapi.PyTorchJob.from_dict(d)

    def set_defaults(self, job: ptapi.PyTorchJob) -> None:
        ptapi.set_defaults(job)

    def validate(self, job: ptapi.PyTorchJob) -> None:
        ptapi.validate(job)

    def set_cluster_spec(
        self, job: ptapi.PyTorchJob, pod_template: Dict[str, Any], rtype: str, index: int
    ) -> None:
        rank = index
        addr = JobEngine.gen_general_name(job.name, ptapi.REPLICA_MASTER, 0)
        if rtype == ptapi.REPLICA_MASTER:
            if rank != 0:
                raise ValidationError(
                    "invalid config: There should be only a single master with index=0"
                )
            addr = "localhost"
        else:
            rank = rank + 1  # master offset (reference pytorch.go:32-39)
        env = {
            "MASTER_PORT": str(master_port(job)),
            "MASTER_ADDR": addr,
            "WORLD_SIZE": str(total_replicas(job)),
            "RANK": str(rank),
            "PYTHONUNBUFFERED": "0",
        }
        for c in pod_template.get("spec", {}).get("containers", []) or []:
            for k, v in env.items():
                objects.set_env(c, k, v)

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        return rtype == ptapi.REPLICA_MASTER

    def replica_order(self, replicas):
        return [rt for rt in (ptapi.REPLICA_MASTER, ptapi.REPLICA_WORKER) if rt in replicas]

    def update_job_status(self, engine, job, ctx: StatusContext) -> None:
        master_based_update_job_status(
            self.KIND, job, ctx, master_type=ptapi.REPLICA_MASTER
        )
