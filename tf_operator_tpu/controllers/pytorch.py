"""PyTorchJob controller adapter — MASTER_ADDR/RANK env + master-gated status.

Reference parity: pkg/controller.v1/pytorch/{pytorch.go,pytorchjob_controller.go}.
Env injection SetPodEnv (pytorch.go:13-68): MASTER_ADDR is the master-0
service name ('localhost' on the master itself), RANK is worker index+1,
WORLD_SIZE is the replica sum — applied to ALL containers.
"""
from __future__ import annotations

from typing import Any, Dict

from tf_operator_tpu.api import common
from tf_operator_tpu.api import pytorch as ptapi
from tf_operator_tpu.api.job import ValidationError
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.controller import JobEngine
from tf_operator_tpu.controllers.shared_status import master_based_update_job_status
from tf_operator_tpu.k8s import objects


def total_replicas(job: ptapi.PyTorchJob) -> int:
    return sum(s.replicas or 0 for s in (job.replica_specs or {}).values())


def master_port(job: ptapi.PyTorchJob) -> int:
    spec = (job.replica_specs or {}).get(ptapi.REPLICA_MASTER)
    if spec is None:
        return ptapi.DEFAULT_PORT
    return objects.replica_port(
        spec.template, ptapi.DEFAULT_CONTAINER_NAME,
        ptapi.DEFAULT_PORT_NAME, ptapi.DEFAULT_PORT,
    )


class PyTorchAdapter(FrameworkAdapter):
    KIND = ptapi.KIND
    PLURAL = ptapi.PLURAL
    REPLICA_TYPES = ptapi.REPLICA_TYPES
    CONTAINER_NAME = ptapi.DEFAULT_CONTAINER_NAME
    PORT_NAME = ptapi.DEFAULT_PORT_NAME
    DEFAULT_PORT = ptapi.DEFAULT_PORT

    def from_dict(self, d: Dict[str, Any]) -> ptapi.PyTorchJob:
        return ptapi.PyTorchJob.from_dict(d)

    def set_defaults(self, job: ptapi.PyTorchJob) -> None:
        ptapi.set_defaults(job)

    def validate(self, job: ptapi.PyTorchJob) -> None:
        ptapi.validate(job)

    def set_cluster_spec(
        self, job: ptapi.PyTorchJob, pod_template: Dict[str, Any], rtype: str, index: int
    ) -> None:
        if job.elastic_policy is not None:
            env = self._elastic_env(job)
        else:
            rank = index
            addr = JobEngine.gen_general_name(job.name, ptapi.REPLICA_MASTER, 0)
            if rtype == ptapi.REPLICA_MASTER:
                if rank != 0:
                    raise ValidationError(
                        "invalid config: There should be only a single master with index=0"
                    )
                addr = "localhost"
            else:
                rank = rank + 1  # master offset (reference pytorch.go:32-39)
            env = {
                "MASTER_PORT": str(master_port(job)),
                "MASTER_ADDR": addr,
                "WORLD_SIZE": str(total_replicas(job)),
                "RANK": str(rank),
                "PYTHONUNBUFFERED": "0",
            }
        for c in pod_template.get("spec", {}).get("containers", []) or []:
            for k, v in env.items():
                objects.set_env(c, k, v)

    @staticmethod
    def _elastic_env(job: ptapi.PyTorchJob) -> Dict[str, str]:
        """torchrun/torch-elastic rendezvous env (PET_* — the variables
        torchrun's launcher reads) instead of static MASTER_*/RANK: the
        rendezvous endpoint is worker-0's stable DNS name (or an explicit
        rdzvHost, e.g. an external etcd), and membership floats between
        min and max as replicas are edited — no env rewrite needed on
        scale, which is the point: the sparse-config analogue of TFJob's
        EnableDynamicWorker (modern training-operator semantics; the
        reference snapshot has no elastic mode)."""
        ep = job.elastic_policy
        host = ep.rdzv_host or JobEngine.gen_general_name(
            job.name, ptapi.REPLICA_WORKER, 0
        )
        # bounds come ONLY from the policy (min defaulted to 1 in
        # set_defaults, max required by validation) so pods created before
        # and after a replica edit always agree on PET_NNODES
        env = {
            "PET_RDZV_BACKEND": ep.rdzv_backend,
            "PET_RDZV_ENDPOINT": f"{host}:{ep.rdzv_port}",
            "PET_RDZV_ID": ep.rdzv_id or job.name,
            "PET_NNODES": f"{ep.min_replicas}:{ep.max_replicas}",
            "PYTHONUNBUFFERED": "0",
        }
        if ep.n_proc_per_node is not None:
            env["PET_NPROC_PER_NODE"] = str(ep.n_proc_per_node)
        if ep.max_restarts is not None:
            env["PET_MAX_RESTARTS"] = str(ep.max_restarts)
        return env

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        if ptapi.REPLICA_MASTER in replicas:
            return rtype == ptapi.REPLICA_MASTER
        # elastic worker-only jobs: worker-0 carries the master role label
        # (it also hosts the c10d rendezvous endpoint)
        return rtype == ptapi.REPLICA_WORKER and index == 0

    def replica_order(self, replicas):
        return [rt for rt in (ptapi.REPLICA_MASTER, ptapi.REPLICA_WORKER) if rt in replicas]

    def update_job_status(self, engine, job, ctx: StatusContext) -> None:
        with engine.tracer.span("PyTorchJob.status_rules"):
            if (
                job.elastic_policy is not None
                and ptapi.REPLICA_MASTER not in ctx.replicas
            ):
                self._elastic_update_job_status(job, ctx)
                return
            master_based_update_job_status(
                self.KIND, job, ctx, master_type=ptapi.REPLICA_MASTER
            )

    def _elastic_update_job_status(self, job, ctx: StatusContext) -> None:
        """Worker-only elastic jobs (torchrun rendezvous, no Master): a
        worker completing cleanly completes the job — elastic agents exit
        together when training finishes, and stragglers are torn down by
        CleanPodPolicy (modern training-operator elastic semantics).

        Failures are evaluated FIRST: in a mixed outcome (one agent exits 0
        while others fail permanently — straggler crash, scale-down race)
        the job must record Failed, and terminal conditions are sticky, so
        marking Succeeded here would make Failed unrecordable forever."""
        from tf_operator_tpu.controllers.shared_status import (
            handle_replica_failure,
            keep_running_tail,
            mark_succeeded,
        )

        rtype = ptapi.REPLICA_WORKER
        spec = ctx.replicas[rtype]
        _, _, succeeded, failed = ctx.counts(rtype)
        if handle_replica_failure(self.KIND, job, ctx, rtype, spec, failed):
            return
        if succeeded > 0:
            mark_succeeded(self.KIND, job, ctx)
            return
        keep_running_tail(self.KIND, job, ctx)
