"""TFJob controller adapter — TF_CONFIG injection + TF status rules.

Reference parity: pkg/controller.v1/tensorflow/{tensorflow.go,status.go,
tfjob_controller.go}. The env-injection seam is SetClusterSpec
(tfjob_controller.go:540-573); cluster-spec DNS form and sparse variant are
tensorflow.go:97-173; status ordering and chief-vs-worker0 success rules are
status.go:64-220.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List

from tf_operator_tpu.api import common
from tf_operator_tpu.api import tensorflow as tfapi
from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.controller import (
    JobEngine,
    REASON_FAILED,
    REASON_RUNNING,
    REASON_SUCCEEDED,
)
from tf_operator_tpu.k8s import objects

ENV_CUSTOM_CLUSTER_DOMAIN = "CUSTOM_CLUSTER_DOMAIN"
ENV_TF_CONFIG = "TF_CONFIG"

# status iteration order (reference status.go:95-101)
STATUS_ORDER = [
    tfapi.REPLICA_CHIEF,
    tfapi.REPLICA_EVALUATOR,
    tfapi.REPLICA_MASTER,
    tfapi.REPLICA_PS,
    tfapi.REPLICA_WORKER,
]


def replica_dns_name(
    job_name: str, namespace: str, rtype: str, index: int, port: int
) -> str:
    """{job}-{rt}-{i}.{ns}.svc[.{domain}]:{port} (reference tensorflow.go:153-166)."""
    host = f"{JobEngine.gen_general_name(job_name, rtype, index)}.{namespace}.svc"
    domain = os.environ.get(ENV_CUSTOM_CLUSTER_DOMAIN, "")
    if domain:
        host += "." + domain
    return f"{host}:{port}"


def gen_cluster_spec(tfjob: tfapi.TFJob) -> Dict[str, List[str]]:
    """reference genClusterSpec (tensorflow.go:142-173)."""
    cluster: Dict[str, List[str]] = {}
    port = tfapi.get_port(tfjob)
    for rtype, spec in (tfjob.replica_specs or {}).items():
        rt = rtype.lower()
        cluster[rt] = [
            replica_dns_name(tfjob.name, tfjob.namespace, rtype, i, port)
            for i in range(spec.replicas or 0)
        ]
    return cluster


def sparse_cluster_spec(
    cluster: Dict[str, List[str]], rtype: str, index: int
) -> Dict[str, Any]:
    """Sparse variant for EnableDynamicWorker: a worker sees only itself plus
    all PS; a PS sees only itself (reference
    convertClusterSpecToSparseClusterSpec, tensorflow.go:64-83)."""
    rt = rtype.lower()
    sparse: Dict[str, Any] = {"worker": {}, "ps": []}
    if rt == "ps":
        sparse["ps"] = [cluster[rt][index]]
    elif rt == "worker":
        sparse["ps"] = cluster.get("ps", [])
        sparse["worker"] = {index: cluster[rt][index]}
    return sparse


def gen_tf_config(tfjob: tfapi.TFJob, rtype: str, index: int) -> str:
    """reference genTFConfigJSONStr (tensorflow.go:97-139)."""
    cluster = gen_cluster_spec(tfjob)
    task = {"type": rtype.lower(), "index": index}
    if tfjob.enable_dynamic_worker:
        payload: Dict[str, Any] = {
            "sparseCluster": sparse_cluster_spec(cluster, rtype, index),
            "task": task,
        }
    else:
        payload = {"cluster": cluster, "task": task, "environment": "cloud"}
    return json.dumps(payload)


def is_distributed(tfjob: tfapi.TFJob) -> bool:
    """>1 total replicas (reference pod.go:298-319)."""
    total = 0
    for spec in (tfjob.replica_specs or {}).values():
        total += spec.replicas if spec.replicas is not None else 1
    return total != 1


class TFAdapter(FrameworkAdapter):
    KIND = tfapi.KIND
    PLURAL = tfapi.PLURAL
    REPLICA_TYPES = tfapi.REPLICA_TYPES
    CONTAINER_NAME = tfapi.DEFAULT_CONTAINER_NAME
    PORT_NAME = tfapi.DEFAULT_PORT_NAME
    DEFAULT_PORT = tfapi.DEFAULT_PORT

    def from_dict(self, d: Dict[str, Any]) -> tfapi.TFJob:
        return tfapi.TFJob.from_dict(d)

    def set_defaults(self, job: tfapi.TFJob) -> None:
        tfapi.set_defaults(job)

    def validate(self, job: tfapi.TFJob) -> None:
        tfapi.validate(job)

    def set_cluster_spec(
        self, job: tfapi.TFJob, pod_template: Dict[str, Any], rtype: str, index: int
    ) -> None:
        if not is_distributed(job):
            return  # no TF_CONFIG for local jobs (reference tfjob_controller.go:547)
        tf_config = gen_tf_config(job, rtype, index)
        c = objects.find_container(pod_template, self.CONTAINER_NAME)
        if c is not None:
            objects.set_env(c, ENV_TF_CONFIG, tf_config)

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        """Chief/Master if present; else worker-0
        (reference tfjob_controller.go:586-593)."""
        if any(tfapi.is_chief_or_master(rt) for rt in replicas):
            return tfapi.is_chief_or_master(rtype)
        return rtype == tfapi.REPLICA_WORKER and index == 0

    def replica_order(self, replicas: Dict[str, common.ReplicaSpec]) -> List[str]:
        return [rt for rt in STATUS_ORDER if rt in replicas] + [
            rt for rt in replicas if rt not in STATUS_ORDER
        ]

    # ------------------------------------------------------------- status
    def _is_worker0_completed(self, ctx: StatusContext) -> bool:
        """worker-0 pod Succeeded with exit code 0
        (reference tfjob_controller.go:597-617)."""
        if tfapi.REPLICA_WORKER not in ctx.replicas:
            return True
        workers = JobEngine.filter_for_replica_type(ctx.pods, tfapi.REPLICA_WORKER)
        for pod in workers:
            if objects.labels_of(pod).get(objects.LABEL_REPLICA_INDEX) != "0":
                continue
            exit_code = objects.container_exit_code(pod, self.CONTAINER_NAME)
            return (
                objects.pod_phase(pod) == objects.POD_SUCCEEDED and exit_code in (0, 0xBEEF)
            )
        return False

    def update_job_status(self, engine: JobEngine, job: tfapi.TFJob, ctx: StatusContext) -> None:
        with engine.tracer.span("TFJob.status_rules"):
            self._update_job_status(engine, job, ctx)

    def _update_job_status(
        self, engine: JobEngine, job: tfapi.TFJob, ctx: StatusContext
    ) -> None:
        """reference UpdateJobStatus (status.go:64-220): chief presence decides
        the success source; worker-0 completion is the chief-less fallback;
        Restarting precedence over Failed."""
        status = ctx.status
        worker0_completed = self._is_worker0_completed(ctx)
        has_chief = tfapi.contains_chief_or_master(job)

        for rtype in self.replica_order(ctx.replicas):
            if common.is_finished(status):
                # first terminal condition wins — later types must not fire
                # success/failure events or metrics on a finished job
                break
            expected, running, succeeded, failed = ctx.counts(rtype)

            if has_chief:
                if tfapi.is_chief_or_master(rtype):
                    if running > 0:
                        common.update_job_conditions(
                            status, common.JOB_RUNNING, REASON_RUNNING,
                            f"TFJob {job.namespace}/{job.name} is running.", ctx.now,
                        )
                    if expected == 0:
                        msg = f"TFJob {job.namespace}/{job.name} successfully completed."
                        ctx.record_event("Normal", REASON_SUCCEEDED, msg)
                        if status.completion_time is None:
                            status.completion_time = ctx.now
                        common.update_job_conditions(
                            status, common.JOB_SUCCEEDED, REASON_SUCCEEDED, msg, ctx.now
                        )
                        metrics.JOBS_SUCCEEDED.inc({"job_namespace": job.namespace})
            else:
                if rtype == tfapi.REPLICA_WORKER:
                    # success: all workers done, or worker-0 done under the
                    # default success policy (reference status.go:150-181)
                    all_workers_done = expected == 0
                    if all_workers_done or (
                        worker0_completed
                        and job.success_policy != tfapi.SUCCESS_POLICY_ALL_WORKERS
                    ):
                        msg = f"TFJob {job.namespace}/{job.name} successfully completed."
                        ctx.record_event("Normal", REASON_SUCCEEDED, msg)
                        if status.completion_time is None:
                            status.completion_time = ctx.now
                        common.update_job_conditions(
                            status, common.JOB_SUCCEEDED, REASON_SUCCEEDED, msg, ctx.now
                        )
                        metrics.JOBS_SUCCEEDED.inc({"job_namespace": job.namespace})
                    elif running > 0:
                        common.update_job_conditions(
                            status, common.JOB_RUNNING, REASON_RUNNING,
                            f"TFJob {job.namespace}/{job.name} is running.", ctx.now,
                        )

            if failed > 0:
                # per-sync engine restart signal, not the lingering condition
                # (deliberate fix of the reference's status.go:186-196 wedge
                # when a retryable and a permanent failure co-occur)
                if rtype in ctx.restarted_types:
                    metrics.JOBS_FAILED.inc({"job_namespace": job.namespace})
                else:
                    msg = (
                        f"TFJob {job.namespace}/{job.name} has failed because "
                        f"{failed} {rtype} replica(s) failed."
                    )
                    ctx.record_event("Normal", REASON_FAILED, msg)
                    if status.completion_time is None:
                        status.completion_time = ctx.now
                    common.update_job_conditions(
                        status, common.JOB_FAILED, REASON_FAILED, msg, ctx.now
                    )
                    metrics.JOBS_FAILED.inc({"job_namespace": job.namespace})
