"""TPUServingJob controller adapter — an independent-replica serving fleet.

The training adapters model gangs (atomic admission, whole-slice restart,
all-hosts success).  A serving fleet inverts every one of those rules:

  - **INDEPENDENT_REPLICAS**: replicas are admitted, placed, restarted,
    and drained one at a time.  The engine skips cluster-scheduler gang
    admission and the PodGroup seam entirely (a fleet never waits on
    "all N or nothing" — a partially-provisioned fleet serves at reduced
    capacity), and a replicas edit is a plain fleet resize, never the
    elastic drain → reshard → resume machine (there is no cross-replica
    training state; scale-in coordination is the ROUTER's job —
    engine/servefleet.py drains dispatch before the pod is deleted).
  - replicas stay warm-pool-claimable: the slice-shape annotation
    api/servingjob.set_defaults stamps on the template routes each pod
    through the same claim-before-create seam as every training pod,
    which is what makes telemetry-driven scale-out fast enough to matter
    (one claim latency instead of a cold image pull).
  - status: Running while ANY replica serves (the fleet degrades, it
    does not die); Failed only when every replica failed permanently
    and nothing is restarting.

Cluster env: each replica learns its own identity and the fleet shape —
enough for a replica to register itself with the router and export
per-replica occupancy telemetry under a stable id.
"""
from __future__ import annotations

from typing import Any, Dict

from tf_operator_tpu.api import common
from tf_operator_tpu.api import servingjob as servingapi
from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.controller import (
    JobEngine,
    REASON_FAILED,
    REASON_RUNNING,
    REASON_SUCCEEDED,
)
from tf_operator_tpu.k8s import objects


class ServingAdapter(FrameworkAdapter):
    KIND = servingapi.KIND
    PLURAL = servingapi.PLURAL
    REPLICA_TYPES = servingapi.REPLICA_TYPES
    CONTAINER_NAME = servingapi.DEFAULT_CONTAINER_NAME
    PORT_NAME = servingapi.DEFAULT_PORT_NAME
    DEFAULT_PORT = servingapi.DEFAULT_PORT
    # the one switch the engine reads: no gang admission, no PodGroup,
    # no elastic-resize phase machine — replicas are independent
    INDEPENDENT_REPLICAS = True

    def from_dict(self, d: Dict[str, Any]) -> servingapi.TPUServingJob:
        return servingapi.TPUServingJob.from_dict(d)

    def set_defaults(self, job: servingapi.TPUServingJob) -> None:
        servingapi.set_defaults(job)

    def validate(self, job: servingapi.TPUServingJob) -> None:
        servingapi.validate(job)

    def set_cluster_spec(
        self, job: servingapi.TPUServingJob, pod_template: Dict[str, Any],
        rtype: str, index: int,
    ) -> None:
        spec = (job.replica_specs or {}).get(rtype)
        port = objects.replica_port(
            spec.template if spec else pod_template,
            servingapi.DEFAULT_CONTAINER_NAME,
            servingapi.DEFAULT_PORT_NAME,
            servingapi.DEFAULT_PORT,
        )
        env = {
            # stable replica identity: the router keys live occupancy
            # telemetry and dispatch bookkeeping on this
            "SERVING_REPLICA_ID": JobEngine.gen_general_name(
                job.name, rtype, index
            ),
            "SERVING_REPLICA_INDEX": str(index),
            "SERVING_FLEET_SIZE": str(
                (spec.replicas if spec else None) or 1
            ),
            "SERVING_JOB": f"{job.namespace}/{job.name}",
            "SERVING_PORT": str(port),
            "TPU_SLICE_SHAPE": job.slice_shape,
        }
        c = objects.find_container(pod_template, self.CONTAINER_NAME)
        targets = (
            [c]
            if c is not None
            else pod_template.get("spec", {}).get("containers", []) or []
        )
        for container in targets:
            for k, v in env.items():
                objects.set_env(container, k, v)

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        return False  # a fleet has no master; the router is outside it

    def update_job_status(self, engine: JobEngine, job, ctx: StatusContext) -> None:
        with engine.tracer.span("TPUServingJob.status_rules"):
            self._update_job_status(engine, job, ctx)

    def _update_job_status(
        self, engine: JobEngine, job, ctx: StatusContext
    ) -> None:
        """Fleet semantics: Running while ANY replica is active (a
        degraded fleet still serves); Failed only when every replica
        failed permanently with nothing restarting; Succeeded when every
        replica exited clean (batch-inference fleets)."""
        status = ctx.status
        rtype = servingapi.REPLICA_REPLICA
        if rtype not in ctx.replicas:
            return
        expected, active, succeeded, failed = ctx.counts(rtype)
        desired = ctx.replicas[rtype].replicas or 0
        if active > 0:
            common.update_job_conditions(
                status, common.JOB_RUNNING, REASON_RUNNING,
                f"TPUServingJob {job.namespace}/{job.name} is serving "
                f"({active}/{desired} replica(s) ready).", ctx.now,
            )
        if desired > 0 and expected == 0 and succeeded > 0:
            msg = (
                f"TPUServingJob {job.namespace}/{job.name} completed: all "
                f"replicas exited cleanly."
            )
            ctx.record_event("Normal", REASON_SUCCEEDED, msg)
            if status.completion_time is None:
                status.completion_time = ctx.now
            common.update_job_conditions(
                status, common.JOB_SUCCEEDED, REASON_SUCCEEDED, msg, ctx.now
            )
            metrics.JOBS_SUCCEEDED.inc({"job_namespace": job.namespace})
        elif (
            failed > 0 and active == 0 and rtype not in ctx.restarted_types
        ):
            msg = (
                f"TPUServingJob {job.namespace}/{job.name} has failed: "
                f"{failed} replica(s) failed permanently and none are "
                f"serving."
            )
            ctx.record_event("Normal", REASON_FAILED, msg)
            if status.completion_time is None:
                status.completion_time = ctx.now
            common.update_job_conditions(
                status, common.JOB_FAILED, REASON_FAILED, msg, ctx.now
            )
            metrics.JOBS_FAILED.inc({"job_namespace": job.namespace})
