"""TPUJob controller adapter — JAX/TPU distributed env + slice semantics.

The TPU-native analogue of the reference's TF_CONFIG seam
(tfjob_controller.go:540-573): instead of a gRPC peer list, a TPU slice
needs (a) the jax.distributed coordinator rendezvous, (b) per-host identity
(TPU_WORKER_ID), (c) the slice hostname roster (TPU_WORKER_HOSTNAMES), and
(d) multislice (DCN) wiring via MEGASCALE_* when numSlices > 1. Collectives
then ride ICI within the slice and DCN across slices — no per-peer service
mesh required (SURVEY.md §5.8).

Slice differences vs the reference's per-pod model:
  - gang scheduling is mandatory (minAvailable = all hosts, set in defaults)
  - restart is whole-slice-atomic (WHOLE_SLICE_RESTART -> engine tears down
    every host pod on a retryable failure)
  - success requires ALL hosts to complete (SPMD: every host runs the same
    program and exits together)
"""
from __future__ import annotations

from typing import Any, Dict, List

from tf_operator_tpu.api import common
from tf_operator_tpu.api import tpujob as tpuapi
from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.controller import (
    JobEngine,
    REASON_FAILED,
    REASON_RUNNING,
    REASON_SUCCEEDED,
)
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.controllers.tensorflow import replica_dns_name


class TPUAdapter(FrameworkAdapter):
    KIND = tpuapi.KIND
    PLURAL = tpuapi.PLURAL
    REPLICA_TYPES = tpuapi.REPLICA_TYPES
    CONTAINER_NAME = tpuapi.DEFAULT_CONTAINER_NAME
    PORT_NAME = tpuapi.DEFAULT_PORT_NAME
    DEFAULT_PORT = tpuapi.DEFAULT_PORT
    WHOLE_SLICE_RESTART = True

    def from_dict(self, d: Dict[str, Any]) -> tpuapi.TPUJob:
        return tpuapi.TPUJob.from_dict(d)

    def set_defaults(self, job: tpuapi.TPUJob) -> None:
        tpuapi.set_defaults(job)

    def validate(self, job: tpuapi.TPUJob) -> None:
        tpuapi.validate(job)

    def set_cluster_spec(
        self, job: tpuapi.TPUJob, pod_template: Dict[str, Any], rtype: str, index: int
    ) -> None:
        hosts_per_slice = tpuapi.slice_hosts(job.accelerator_type)
        num_slices = max(1, job.num_slices)
        slice_id, host_in_slice = divmod(index, hosts_per_slice)
        total_hosts = hosts_per_slice * num_slices

        def host_dns(i: int) -> str:
            return replica_dns_name(
                job.name, job.namespace, rtype, i, 0
            ).rsplit(":", 1)[0]

        # roster of hosts within THIS replica's slice
        slice_base = slice_id * hosts_per_slice
        slice_hostnames = ",".join(
            host_dns(slice_base + i) for i in range(hosts_per_slice)
        )
        # honor a declared coordinator container port (set_defaults injects
        # the default; users may override — same contract as PyTorch's
        # master_port honoring the declared pytorchjob-port)
        spec = (job.replica_specs or {}).get(rtype)
        coord_port = objects.replica_port(
            spec.template if spec else pod_template,
            tpuapi.DEFAULT_CONTAINER_NAME,
            tpuapi.COORDINATOR_PORT_NAME,
            tpuapi.DEFAULT_COORDINATOR_PORT,
        )
        coordinator = f"{host_dns(slice_base)}:{coord_port}"
        env = {
            # jax.distributed.initialize() rendezvous (per slice)
            "COORDINATOR_ADDRESS": coordinator,
            "NUM_PROCESSES": str(hosts_per_slice),
            "PROCESS_ID": str(host_in_slice),
            # libtpu host identity/roster
            "TPU_WORKER_ID": str(host_in_slice),
            "TPU_WORKER_HOSTNAMES": slice_hostnames,
            "TPU_ACCELERATOR_TYPE": job.accelerator_type,
            # runtime mesh construction hints
            "TPU_SLICE_ID": str(slice_id),
            "TPU_NUM_SLICES": str(num_slices),
            "TPU_HOSTS_PER_SLICE": str(hosts_per_slice),
            "TPU_TOTAL_HOSTS": str(total_hosts),
        }
        if job.topology:
            env["TPU_TOPOLOGY"] = job.topology
        if num_slices > 1:
            # multislice-over-DCN wiring (MEGASCALE convention)
            env["MEGASCALE_COORDINATOR_ADDRESS"] = f"{host_dns(0)}:{coord_port}"
            env["MEGASCALE_NUM_SLICES"] = str(num_slices)
            env["MEGASCALE_SLICE_ID"] = str(slice_id)
        c = objects.find_container(pod_template, self.CONTAINER_NAME)
        targets = (
            [c]
            if c is not None
            else pod_template.get("spec", {}).get("containers", []) or []
        )
        for container in targets:
            for k, v in env.items():
                objects.set_env(container, k, v)

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        return rtype == tpuapi.REPLICA_WORKER and index == 0  # coordinator host

    def update_job_status(self, engine: JobEngine, job, ctx: StatusContext) -> None:
        with engine.tracer.span("TPUJob.status_rules"):
            self._update_job_status(engine, job, ctx)

    def _update_job_status(
        self, engine: JobEngine, job, ctx: StatusContext
    ) -> None:
        """All-hosts semantics: Running while any host runs; Succeeded only
        when every host completed; a non-retryable failure (engine didn't
        convert it to Restarting) fails the job."""
        status = ctx.status
        rtype = tpuapi.REPLICA_WORKER
        if rtype not in ctx.replicas:
            return
        expected, running, succeeded, failed = ctx.counts(rtype)
        if running > 0:
            common.update_job_conditions(
                status, common.JOB_RUNNING, REASON_RUNNING,
                f"TPUJob {job.namespace}/{job.name} is running "
                f"({running} hosts active).", ctx.now,
            )
        if expected == 0:
            msg = f"TPUJob {job.namespace}/{job.name} successfully completed."
            ctx.record_event("Normal", REASON_SUCCEEDED, msg)
            if status.completion_time is None:
                status.completion_time = ctx.now
            common.update_job_conditions(
                status, common.JOB_SUCCEEDED, REASON_SUCCEEDED, msg, ctx.now
            )
            metrics.JOBS_SUCCEEDED.inc({"job_namespace": job.namespace})
        elif failed > 0:
            if rtype not in ctx.restarted_types:
                msg = (
                    f"TPUJob {job.namespace}/{job.name} has failed because "
                    f"{failed} {rtype} host(s) failed permanently."
                )
                ctx.record_event("Normal", REASON_FAILED, msg)
                if status.completion_time is None:
                    status.completion_time = ctx.now
                common.update_job_conditions(
                    status, common.JOB_FAILED, REASON_FAILED, msg, ctx.now
                )
                metrics.JOBS_FAILED.inc({"job_namespace": job.namespace})
