"""Controller registry — Kind -> adapter, the '--enable-scheme' surface
(reference register_controller.go:36-76: SupportedSchemeReconciler +
EnabledSchemes)."""
from __future__ import annotations

from typing import Dict, List, Optional, Type

from tf_operator_tpu.engine.adapter import FrameworkAdapter
from tf_operator_tpu.engine.controller import EngineConfig, JobEngine
from tf_operator_tpu.controllers.tensorflow import TFAdapter
from tf_operator_tpu.controllers.pytorch import PyTorchAdapter
from tf_operator_tpu.controllers.mxnet import MXNetAdapter
from tf_operator_tpu.controllers.xgboost import XGBoostAdapter
from tf_operator_tpu.controllers.tpu import TPUAdapter
from tf_operator_tpu.controllers.serving import ServingAdapter

SUPPORTED_ADAPTERS: Dict[str, Type[FrameworkAdapter]] = {
    TFAdapter.KIND: TFAdapter,
    PyTorchAdapter.KIND: PyTorchAdapter,
    MXNetAdapter.KIND: MXNetAdapter,
    XGBoostAdapter.KIND: XGBoostAdapter,
    TPUAdapter.KIND: TPUAdapter,
    ServingAdapter.KIND: ServingAdapter,
}


class EnabledSchemes:
    """Validating multi-value flag type (reference register_controller.go:51-76)."""

    def __init__(self, kinds: Optional[List[str]] = None) -> None:
        self.kinds: List[str] = []
        for k in kinds or []:
            self.set(k)

    def set(self, kind: str) -> None:
        match = next(
            (k for k in SUPPORTED_ADAPTERS if k.lower() == kind.lower()), None
        )
        if match is None:
            raise ValueError(
                f"kind {kind!r} is not supported; supported: "
                f"{sorted(SUPPORTED_ADAPTERS)}"
            )
        if match not in self.kinds:
            self.kinds.append(match)

    def fill_all(self) -> None:
        self.kinds = list(SUPPORTED_ADAPTERS)

    def empty(self) -> bool:
        return not self.kinds


def make_engine(
    kind: str, cluster, config: Optional[EngineConfig] = None, **kwargs
) -> JobEngine:
    adapter_cls = SUPPORTED_ADAPTERS.get(kind)
    if adapter_cls is None:
        raise ValueError(f"unsupported job kind {kind!r}")
    return JobEngine(cluster, adapter_cls(), config=config, **kwargs)
