from tf_operator_tpu.controllers.registry import SUPPORTED_ADAPTERS, make_engine

__all__ = ["SUPPORTED_ADAPTERS", "make_engine"]
