"""MXJob controller adapter — MX_CONFIG + DMLC_* env, scheduler rendezvous.

Reference parity: pkg/controller.v1/mxnet/{mxnet.go,mxjob_controller.go}.
Env (mxnet.go:55-120): MX_CONFIG JSON {cluster:{rt:[{url,port}]}, labels,
task}, DMLC_PS_ROOT_URI/PORT from scheduler-0, DMLC_NUM_SERVER/WORKER,
DMLC_ROLE, DMLC_USE_KUBERNETES, BytePS DMLC_WORKER_ID; tvm auto-tuning
'tuner-server-key' annotation passthrough (mxnet.go:16-19).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List

from tf_operator_tpu.api import common
from tf_operator_tpu.api import mxnet as mxapi
from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine.adapter import FrameworkAdapter, StatusContext
from tf_operator_tpu.engine.controller import (
    JobEngine,
    REASON_FAILED,
    REASON_RESTARTING,
    REASON_RUNNING,
    REASON_SUCCEEDED,
)
from tf_operator_tpu.k8s import objects

TUNER_SERVER_KEY = "tuner-server-key"
ENV_MX_CONFIG = "MX_CONFIG"


def get_port(job: mxapi.MXJob, rtype: str) -> int:
    spec = (job.replica_specs or {}).get(rtype)
    if spec is None:
        return mxapi.DEFAULT_PORT
    return objects.replica_port(
        spec.template, mxapi.DEFAULT_CONTAINER_NAME,
        mxapi.DEFAULT_PORT_NAME, mxapi.DEFAULT_PORT,
    )


def gen_cluster_spec(job: mxapi.MXJob) -> Dict[str, List[Dict[str, Any]]]:
    """{rt: [{url, port}...]} — url is the bare service name (same-namespace
    DNS), reference genClusterSpec (mxnet.go:122-175)."""
    cluster: Dict[str, List[Dict[str, Any]]] = {}
    for rtype, spec in (job.replica_specs or {}).items():
        rt = rtype.lower()
        port = get_port(job, rtype)
        cluster[rt] = [
            {"url": JobEngine.gen_general_name(job.name, rtype, i), "port": port}
            for i in range(spec.replicas or 0)
        ]
    return cluster


def gen_labels_spec(job: mxapi.MXJob) -> Dict[str, str]:
    return {
        rtype.lower(): (spec.template.get("metadata", {}).get("annotations", {}) or {}).get(
            TUNER_SERVER_KEY, ""
        )
        for rtype, spec in (job.replica_specs or {}).items()
    }


class MXNetAdapter(FrameworkAdapter):
    KIND = mxapi.KIND
    PLURAL = mxapi.PLURAL
    REPLICA_TYPES = mxapi.REPLICA_TYPES
    CONTAINER_NAME = mxapi.DEFAULT_CONTAINER_NAME
    PORT_NAME = mxapi.DEFAULT_PORT_NAME
    DEFAULT_PORT = mxapi.DEFAULT_PORT

    def from_dict(self, d: Dict[str, Any]) -> mxapi.MXJob:
        return mxapi.MXJob.from_dict(d)

    def set_defaults(self, job: mxapi.MXJob) -> None:
        mxapi.set_defaults(job)

    def validate(self, job: mxapi.MXJob) -> None:
        mxapi.validate(job)

    def set_cluster_spec(
        self, job: mxapi.MXJob, pod_template: Dict[str, Any], rtype: str, index: int
    ) -> None:
        rt = rtype.lower()
        cluster = gen_cluster_spec(job)
        mx_config = {
            "cluster": cluster,
            "labels": gen_labels_spec(job),
            "task": {"type": rt, "index": index},
        }
        scheduler = (cluster.get("scheduler") or [{"url": "", "port": 0}])[0]
        env = {
            ENV_MX_CONFIG: json.dumps(mx_config),
            "DMLC_PS_ROOT_PORT": str(scheduler["port"]),
            "DMLC_PS_ROOT_URI": scheduler["url"],
            "DMLC_NUM_SERVER": str(len(cluster.get("server", []))),
            "DMLC_NUM_WORKER": str(len(cluster.get("worker", []))),
            "DMLC_ROLE": rt,
            "DMLC_USE_KUBERNETES": "1",
        }
        for c in pod_template.get("spec", {}).get("containers", []) or []:
            for k, v in env.items():
                objects.set_env(c, k, v)
            if rt == mxapi.REPLICA_WORKER.lower():
                objects.set_env(c, "DMLC_WORKER_ID", str(index))  # BytePS

    def is_master_role(
        self, replicas: Dict[str, common.ReplicaSpec], rtype: str, index: int
    ) -> bool:
        return mxapi.is_scheduler(rtype)

    def update_job_status(self, engine, job, ctx: StatusContext) -> None:
        with engine.tracer.span("MXJob.status_rules"):
            self._update_job_status(engine, job, ctx)

    def _update_job_status(self, engine, job, ctx: StatusContext) -> None:
        """reference mxjob_controller.go:328-412: Running while any replica
        runs; Succeeded when any replica type fully completes; ExitCode
        failures restart, others fail."""
        status = ctx.status
        for rtype in sorted(ctx.replicas):
            if common.is_finished(status):
                break  # first terminal condition wins (events/metrics too)
            spec = ctx.replicas[rtype]
            expected, running, succeeded, failed = ctx.counts(rtype)
            if running > 0:
                common.update_job_conditions(
                    status, common.JOB_RUNNING, REASON_RUNNING,
                    f"MXJob {job.name} is running.", ctx.now,
                )
            if expected == 0:
                msg = f"MXJob {job.name} is successfully completed."
                ctx.record_event("Normal", REASON_SUCCEEDED, msg)
                if status.completion_time is None:
                    status.completion_time = ctx.now
                common.update_job_conditions(
                    status, common.JOB_SUCCEEDED, REASON_SUCCEEDED, msg, ctx.now
                )
                metrics.JOBS_SUCCEEDED.inc({"job_namespace": job.namespace})
            if failed > 0:
                # see shared_status.py: permanent ExitCode failures must fail
                # the job; only engine-initiated restarts (this sync) stay
                # Restarting
                if (
                    spec.restart_policy == common.RESTART_POLICY_EXIT_CODE
                    and rtype in ctx.restarted_types
                ):
                    pass
                else:
                    msg = (
                        f"MXJob {job.name} is failed because {failed} "
                        f"{rtype} replica(s) failed."
                    )
                    ctx.record_event("Normal", REASON_FAILED, msg)
                    if status.completion_time is None:
                        status.completion_time = ctx.now
                    common.update_job_conditions(
                        status, common.JOB_FAILED, REASON_FAILED, msg, ctx.now
                    )
                    metrics.JOBS_FAILED.inc({"job_namespace": job.namespace})
