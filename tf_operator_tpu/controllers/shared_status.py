"""Master-gated status rules shared by PyTorchJob and XGBoostJob
(reference pytorchjob_controller.go UpdateJobStatus and the near-identical
xgboostjob_controller.go version): Running while the master runs, Succeeded
when the master completes, ExitCode failures become Restarting, other
failures Fail the job; a live job keeps a Running condition.
"""
from __future__ import annotations

from tf_operator_tpu.api import common
from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine.adapter import StatusContext
from tf_operator_tpu.engine.controller import (
    REASON_FAILED,
    REASON_RESTARTING,
    REASON_RUNNING,
    REASON_SUCCEEDED,
)


def mark_succeeded(kind: str, job, ctx: StatusContext) -> None:
    """Record the Succeeded condition + event + metric (shared by the
    master-gated and elastic success rules)."""
    status = ctx.status
    msg = f"{kind} {job.name} is successfully completed."
    ctx.record_event("Normal", REASON_SUCCEEDED, msg)
    if status.completion_time is None:
        status.completion_time = ctx.now
    common.update_job_conditions(
        status, common.JOB_SUCCEEDED, REASON_SUCCEEDED, msg, ctx.now
    )
    metrics.JOBS_SUCCEEDED.inc({"job_namespace": job.namespace})


def handle_replica_failure(
    kind: str, job, ctx: StatusContext, rtype: str, spec, failed: int
) -> bool:
    """Fail the job on a permanent replica failure; returns True when the
    job was failed (callers stop their loop).

    The engine only deletes-for-restart on RETRYABLE exit codes; a failed
    pod still present under ExitCode policy means a permanent (1-127)
    code, which must FAIL the job, not wedge it in Restarting.
    ctx.restarted_types is the per-sync engine signal — checking the
    lingering Restarting *condition* would conflate an old restart with a
    new permanent failure (the reference's wedge,
    pytorchjob_controller.go:359; deliberate fix)."""
    if failed <= 0:
        return False
    if (
        spec.restart_policy == common.RESTART_POLICY_EXIT_CODE
        and rtype in ctx.restarted_types
    ):
        return False  # engine already recorded the restart + condition
    status = ctx.status
    msg = f"{kind} {job.name} is failed because {failed} {rtype} replica(s) failed."
    ctx.record_event("Normal", REASON_FAILED, msg)
    if status.completion_time is None:
        status.completion_time = ctx.now
    common.update_job_conditions(
        status, common.JOB_FAILED, REASON_FAILED, msg, ctx.now
    )
    metrics.JOBS_FAILED.inc({"job_namespace": job.namespace})
    return True


def keep_running_tail(kind: str, job, ctx: StatusContext) -> None:
    """A live job keeps a Running condition (reference
    pytorchjob_controller.go tail)."""
    status = ctx.status
    if not common.is_finished(status) and not common.has_condition(
        status, common.JOB_RESTARTING
    ):
        common.update_job_conditions(
            status, common.JOB_RUNNING, REASON_RUNNING,
            f"{kind} {job.name} is running.", ctx.now,
        )


def master_based_update_job_status(
    kind: str, job, ctx: StatusContext, master_type: str = "Master"
) -> None:
    status = ctx.status
    for rtype in [master_type] + [rt for rt in ctx.replicas if rt != master_type]:
        if rtype not in ctx.replicas:
            continue
        if common.is_finished(status):
            break  # first terminal condition wins (events/metrics too)
        spec = ctx.replicas[rtype]
        expected, running, succeeded, failed = ctx.counts(rtype)

        if rtype == master_type:
            if running > 0:
                common.update_job_conditions(
                    status, common.JOB_RUNNING, REASON_RUNNING,
                    f"{kind} {job.name} is running.", ctx.now,
                )
            if expected == 0:
                mark_succeeded(kind, job, ctx)
                return

        if handle_replica_failure(kind, job, ctx, rtype, spec, failed):
            return
    keep_running_tail(kind, job, ctx)
