"""tf_operator_tpu — a TPU-native distributed-training operator + runtime.

A brand-new framework with the capabilities of Kubeflow's tf-operator
(reference: ryantd/tf-operator): CRD-style job specs (TFJob, PyTorchJob,
MXJob, XGBoostJob, and the new TPUJob), a generic reconciliation engine
(pods + headless services + cluster-discovery env injection + restart /
success / clean-pod policies + status conditions), gang scheduling, metrics,
and a Python client SDK — plus what the reference delegates to in-container
frameworks: a TPU-native compute runtime (JAX/XLA/pallas) with SPMD
parallelism (dp/tp/pp/sp/ep) over `jax.sharding.Mesh`, models, and kernels.

Layer map (mirrors SURVEY.md §1 of the reference analysis):
  k8s/          L0/L1 — cluster-state abstraction: objects, fake cluster,
                informer-style event fanout, real-API client shim
  api/          L2   — job types, defaulting, validation
  engine/       L3   — generic job-controller engine (kubeflow/common equiv.)
  controllers/  L4   — per-framework reconcilers + env injection
  cli/          L5   — operator entrypoint (flags, health, metrics, election)
  manifests/    L6   — CRDs + deployment yaml (repo root)
  sdk/          L7   — user-facing job client
  runtime/, models/, ops/, parallel/ — the TPU compute stack (new; the
                reference leaves this to the containers it schedules)
"""

__version__ = "0.1.0"
