"""Shared informers, listers, and rate-limited workqueues.

The Python equivalent of the reference's generated client machinery
(SURVEY.md §2.5: SharedInformerFactory `pkg/client/informers/
externalversions/factory.go`, listers `pkg/client/listers/tensorflow/v1/
tfjob.go`) plus client-go's workqueue (the legacy controller's hot loop
pops from a rate-limiting queue: reference
pkg/controller.v1/tensorflow/controller.go:230-286).

Design notes (differences from a line-by-line translation, deliberate):
- The cluster store itself (k8s/fake.py FakeCluster) already delivers
  ADDED/MODIFIED/DELETED callbacks, so the informer here is a thin cache +
  handler fan-out + resync layer, not a watch-decoder.
- The queue keeps client-go's exact semantics (dirty/processing sets so an
  item re-added mid-processing is re-delivered exactly once; per-item
  exponential backoff with Forget on success) because the reference's
  correctness depends on them: one worker per job key at a time
  ("syncTFJob is not meant to be invoked concurrently with the same key",
  reference controller.go:299-301).
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.k8s import objects


def capped_exponential(base: float, n: int, cap: float) -> float:
    """base * 2^n clamped to cap, overflow-safe for huge n — THE formula
    behind every backoff ladder in this codebase (workqueue rate limiter,
    watch reconnect, crash-loop restart).  The exponent clamp matters: past
    ~2^60 the product overflows float conversion, and anything that has
    been failing that long is pinned at the cap anyway — found by the
    chaos soak."""
    if base <= 0.0:
        return 0.0
    if n >= 60:
        return cap
    return min(cap, base * (2 ** n))


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff: base * 2^failures, capped.
    (client-go's DefaultControllerRateLimiter core, minus the token bucket —
    the bucket only matters against a real apiserver.)"""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0) -> None:
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return capped_exponential(self.base_delay, n, self.max_delay)

    def forget(self, item: Any) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue:
    """Deduplicating work queue with delayed and rate-limited adds.

    Invariants (client-go workqueue contract):
      - an item is delivered to at most one worker at a time;
      - adding an item already queued is a no-op (dedup);
      - adding an item currently being processed marks it dirty, and it is
        re-queued when the worker calls done();
      - shutdown() wakes all blocked getters, which then receive None.
    """

    def __init__(self, rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None):
        self._cond = threading.Condition()
        self._queue: List[Any] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutdown = False
        self._rate_limiter = rate_limiter or ItemExponentialFailureRateLimiter()
        # delayed adds: heap of (fire_time, seq, item)
        self._heap: List[Tuple[float, int, Any]] = []
        self._seq = 0
        self._timer_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- core
    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Block until an item is available (or shutdown/timeout -> None)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._shutdown:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining if remaining is not None else 0.1)
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._dirty.discard(item)
            self._processing.add(item)
            return item

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty and item not in self._queue:
                self._queue.append(item)
                self._cond.notify()

    # ------------------------------------------------------------- delayed
    def add_after(self, item: Any, delay: float) -> None:
        """Queue `item` after `delay` seconds. The seam the reference's new
        stack broke (FakeWorkQueue.AddAfter is a no-op, reference
        fake_workqueue.go:27) — here it is real and tested."""
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._heap, (time.monotonic() + delay, self._seq, item))
            # the timer thread clears _timer_thread under this lock before it
            # exits, so `is None` is a race-free liveness check (an is_alive()
            # check would miss a thread that decided to exit but hasn't died)
            if self._timer_thread is None:
                self._timer_thread = threading.Thread(
                    target=self._timer_loop, daemon=True
                )
                self._timer_thread.start()
            self._cond.notify()

    def _timer_loop(self) -> None:
        while True:
            with self._cond:
                if self._shutdown or not self._heap:
                    self._timer_thread = None
                    return
                fire_at, _, item = self._heap[0]
                now = time.monotonic()
                if fire_at <= now:
                    heapq.heappop(self._heap)
                    ready = item
                else:
                    self._cond.wait(min(fire_at - now, 0.05))
                    continue
            self.add(ready)

    def add_rate_limited(self, item: Any) -> float:
        """Returns the backoff delay applied, so callers timing queue
        latency can stamp the key's *due* time rather than charging the
        deliberate backoff to the latency histogram."""
        delay = self._rate_limiter.when(item)
        self.add_after(item, delay)
        return delay

    def forget(self, item: Any) -> None:
        self._rate_limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self._rate_limiter.num_requeues(item)

    # ------------------------------------------------------------- lifecycle
    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def pending_delayed(self) -> int:
        with self._cond:
            return len(self._heap)

    def empty(self) -> bool:
        with self._cond:
            return not self._queue and not self._processing

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutdown


# handlers receive the k8s-shaped dict; update handlers receive (old, new)
AddFunc = Callable[[Dict[str, Any]], None]
UpdateFunc = Callable[[Dict[str, Any], Dict[str, Any]], None]
DeleteFunc = Callable[[Dict[str, Any]], None]


class ResourceEventHandler:
    def __init__(
        self,
        add_func: Optional[AddFunc] = None,
        update_func: Optional[UpdateFunc] = None,
        delete_func: Optional[DeleteFunc] = None,
    ) -> None:
        self.add_func = add_func
        self.update_func = update_func
        self.delete_func = delete_func


class SharedIndexInformer:
    """Local cache of one kind + handler fan-out + periodic resync.

    The cache (indexer) is what listers read; tests may also inject fixtures
    directly with `indexer_add` the way the reference's controller tests
    inject into informer indexers (reference job_test.go:40-64).

    "Index" is literal (client-go cache.Indexer): alongside the flat
    key->object cache, two lookup tables are maintained incrementally on
    every event and rebuilt atomically on relist —
      - namespace -> {key: obj}
      - (namespace, job-name label) -> {key: obj}
    so the sync hot path's "pods of job X" read (`Lister.list` with the
    GenLabels selector) is a dict lookup over the job's own O(replicas)
    objects instead of a linear scan of the whole cluster's cache with
    per-object label matching."""

    def __init__(self, cluster, kind: str, resync_period: float = 0.0) -> None:
        self.cluster = cluster
        self.kind = kind
        self.resync_period = resync_period
        self._lock = threading.RLock()
        self._cache: Dict[str, Dict[str, Any]] = {}
        # client-go-style indexes over _cache; every mutation of _cache
        # updates them under the same lock (byte-identical to a from-scratch
        # rebuild at all times — asserted by the churn tests)
        self._ns_index: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._job_index: Dict[Tuple[str, str], Dict[str, Dict[str, Any]]] = {}
        # rv-ordered application guard: FakeCluster (and any concurrent
        # event source) notifies OUTSIDE its store lock, so two writes to
        # the same object can deliver inverted.  Harmless while consumers
        # re-read the store, fatal once this cache IS the read path: a
        # late ADDED would resurrect a deleted pod forever (no further
        # event ever corrects it).  Stale deliveries — rv older than the
        # cached object, or not newer than the key's deletion tombstone —
        # are dropped, cache and dispatch both (client-go's single
        # rv-ordered watch stream makes them impossible by construction;
        # here they must be filtered).  Tombstones are pruned FIFO: they
        # only matter for deliveries inverted across milliseconds.
        self._tombstones: Dict[str, int] = {}
        self._handlers: List[ResourceEventHandler] = []
        self._synced = False
        self._stop = threading.Event()
        self._resync_thread: Optional[threading.Thread] = None
        self._needs_relist = False
        # relist vs concurrent-event guard: while a relist's LIST is in
        # flight, deletes AND upserts observed by _on_event are recorded so
        # the stale list snapshot can neither resurrect an object deleted
        # mid-relist nor clobber (and phantom-DELETE) one created/updated
        # mid-relist
        self._relisting = False
        self._relist_deletes: set = set()
        self._relist_upserts: Dict[str, Dict[str, Any]] = {}
        # one relist at a time: the ERROR-dispatch thread and the resync
        # thread's pending-repair retry would otherwise interleave and
        # clobber the tombstone/upsert state above (plain Lock — never
        # taken while holding self._lock, so no ordering cycle)
        self._relist_mutex = threading.Lock()
        cluster.subscribe(kind, self._on_event)

    # bound on deletion tombstones kept for the rv ordering guard
    MAX_TOMBSTONES = 4096

    @staticmethod
    def _rv_int(obj: Optional[Dict[str, Any]]) -> Optional[int]:
        """Best-effort numeric resourceVersion (k8s rvs are formally opaque
        but etcd revisions compare in practice — same stance as the
        engine's stale-read fence); None disables the ordering guard for
        that comparison."""
        if obj is None:
            return None
        try:
            return int((obj.get("metadata") or {}).get("resourceVersion"))
        except (TypeError, ValueError):
            return None

    # ------------------------------------------------------------- indexes
    def _index_insert(self, key: str, obj: Dict[str, Any]) -> None:
        """Register `obj` under both indexes. Caller holds self._lock."""
        ns = objects.namespace_of(obj)
        self._ns_index.setdefault(ns, {})[key] = obj
        job_name = objects.labels_of(obj).get(objects.LABEL_JOB_NAME)
        if job_name:
            self._job_index.setdefault((ns, job_name), {})[key] = obj

    def _index_remove(self, key: str, obj: Dict[str, Any]) -> None:
        """Drop `obj`'s index entries, using ITS namespace/labels (a MODIFIED
        that moves labels must remove the old coordinates, not the new).
        Empty buckets are pruned so the index never outgrows the cache.
        Caller holds self._lock."""
        ns = objects.namespace_of(obj)
        bucket = self._ns_index.get(ns)
        if bucket is not None:
            bucket.pop(key, None)
            if not bucket:
                del self._ns_index[ns]
        job_name = objects.labels_of(obj).get(objects.LABEL_JOB_NAME)
        if job_name:
            jbucket = self._job_index.get((ns, job_name))
            if jbucket is not None:
                jbucket.pop(key, None)
                if not jbucket:
                    del self._job_index[(ns, job_name)]

    def _cache_upsert(self, key: str, obj: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Insert/replace `key` in cache + indexes; returns the replaced
        object (None for a fresh add). Caller holds self._lock."""
        old = self._cache.get(key)
        if old is not None:
            self._index_remove(key, old)
        self._cache[key] = obj
        self._index_insert(key, obj)
        return old

    def _cache_delete(self, key: str) -> Optional[Dict[str, Any]]:
        """Remove `key` from cache + indexes; returns the removed object.
        Caller holds self._lock."""
        old = self._cache.pop(key, None)
        if old is not None:
            self._index_remove(key, old)
        return old

    @staticmethod
    def build_indexes(
        cache: Dict[str, Dict[str, Any]]
    ) -> Tuple[
        Dict[str, Dict[str, Dict[str, Any]]],
        Dict[Tuple[str, str], Dict[str, Dict[str, Any]]],
    ]:
        """From-scratch (namespace, job) indexes for `cache` — the atomic
        relist rebuild, and the churn tests' ground truth the incremental
        maintenance is compared against."""
        ns_index: Dict[str, Dict[str, Dict[str, Any]]] = {}
        job_index: Dict[Tuple[str, str], Dict[str, Dict[str, Any]]] = {}
        for key, obj in cache.items():
            ns = objects.namespace_of(obj)
            ns_index.setdefault(ns, {})[key] = obj
            job_name = objects.labels_of(obj).get(objects.LABEL_JOB_NAME)
            if job_name:
                job_index.setdefault((ns, job_name), {})[key] = obj
        return ns_index, job_index

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        """List current state into the cache and deliver initial ADDs."""
        initial = self.cluster.list(self.kind)
        skipped = set()
        with self._lock:
            for obj in initial:
                # events race the initial list (subscription opened at
                # construction): state a live event already delivered must
                # not be rolled back by the (possibly older) list snapshot,
                # and a deletion observed since the list must not be
                # resurrected — same rv ordering rules as _on_event.
                # Skipped objects are skipped from dispatch too: an ADDED
                # for state the informer judged dead/stale would leak to
                # handlers what the cache (rightly) refuses to hold.
                key = objects.key_of(obj)
                rv = self._rv_int(obj)
                if rv is not None:
                    tomb = self._tombstones.get(key)
                    if tomb is not None and rv <= tomb:
                        skipped.add(key)
                        continue
                    cur_rv = self._rv_int(self._cache.get(key))
                    if cur_rv is not None and rv < cur_rv:
                        skipped.add(key)
                        continue
                self._cache_upsert(key, obj)
            self._synced = True
        for obj in initial:
            if objects.key_of(obj) not in skipped:
                self._dispatch("ADDED", obj, None)
        if self.resync_period > 0 and self._resync_thread is None:
            self._resync_thread = threading.Thread(target=self._resync_loop, daemon=True)
            self._resync_thread.start()

    def stop(self) -> None:
        self._stop.set()

    def has_synced(self) -> bool:
        return self._synced

    # ------------------------------------------------------------- events
    def add_event_handler(self, handler: ResourceEventHandler) -> None:
        self._handlers.append(handler)

    def _on_event(self, event_type: str, obj: Dict[str, Any]) -> None:
        if event_type == "ERROR":
            # the watch layer lost events it cannot replay (410 Gone /
            # stream gap): repair by relisting and diffing, like client-go's
            # Reflector Replace — re-pinning without the diff would hide
            # whatever happened during the gap forever
            self.relist()
            return
        key = objects.key_of(obj)
        rv = self._rv_int(obj)
        old = None
        with self._lock:
            if event_type == "DELETED":
                cur_rv = self._rv_int(self._cache.get(key))
                if rv is not None and cur_rv is not None and cur_rv > rv:
                    return  # late delete of an older incarnation
                old = self._cache_delete(key)
                if rv is not None:
                    # max(): a LATE-delivered older delete (prior incarnation)
                    # must not regress the tombstone and re-open the window
                    # for that incarnation's stale upserts
                    prev_tomb = self._tombstones.get(key)
                    self._tombstones[key] = (
                        rv if prev_tomb is None else max(rv, prev_tomb)
                    )
                    while len(self._tombstones) > self.MAX_TOMBSTONES:
                        self._tombstones.pop(next(iter(self._tombstones)))
                if self._relisting:
                    self._relist_deletes.add(key)
                    self._relist_upserts.pop(key, None)
            else:
                tomb = self._tombstones.get(key)
                if rv is not None:
                    if tomb is not None and rv <= tomb:
                        return  # upsert older than the key's deletion
                    cur_rv = self._rv_int(self._cache.get(key))
                    if cur_rv is not None and rv < cur_rv:
                        return  # stale delivery: cache already newer
                    if tomb is not None:
                        del self._tombstones[key]  # recreated, newer rv
                old = self._cache_upsert(key, obj)
                if self._relisting:
                    self._relist_upserts[key] = obj
                    self._relist_deletes.discard(key)
        self._dispatch(event_type, obj, old)

    def relist(self) -> bool:
        """Resync the cache from an authoritative list and dispatch the
        DIFF — new objects as adds, changed as updates, vanished as deletes
        (delete events are exactly what a naive cache reset loses).  On a
        failed list (the apiserver may still be erroring) the repair stays
        pending and resync_once retries it.  Deletes and upserts observed
        concurrently with the LIST win over the (already stale) snapshot.
        Returns True on success."""
        with self._relist_mutex:
            return self._relist_locked()

    def _relist_locked(self) -> bool:
        with self._lock:
            self._relisting = True
            self._relist_deletes = set()
            self._relist_upserts = {}
        try:
            current = self.cluster.list(self.kind)
        except Exception:
            with self._lock:
                self._needs_relist = True
                self._relisting = False
            return False
        with self._lock:
            self._needs_relist = False
            self._relisting = False
            mid_deletes, self._relist_deletes = self._relist_deletes, set()
            upserts, self._relist_upserts = self._relist_upserts, {}
            new_cache: Dict[str, Dict[str, Any]] = {}
            for obj in current:
                key = objects.key_of(obj)
                if key in mid_deletes:
                    continue  # deleted while the LIST was in flight
                # the same rv ordering rules as _on_event apply to the
                # snapshot itself: a stale LIST (one-write-behind chaos
                # fault, lagging apiserver cache) must neither resurrect
                # an object whose deletion was already delivered (rv <=
                # its tombstone) nor roll a live object back below state
                # already in the cache — the cache is the sync read path
                # now, and nothing would ever correct either regression
                rv = self._rv_int(obj)
                if rv is not None:
                    tomb = self._tombstones.get(key)
                    if tomb is not None and rv <= tomb:
                        continue
                    cur = self._cache.get(key)
                    cur_rv = self._rv_int(cur)
                    if cur_rv is not None and rv < cur_rv:
                        new_cache[key] = cur  # keep the newer known state
                        continue
                new_cache[key] = obj
            new_cache.update(upserts)  # live events beat the snapshot
            old_cache, self._cache = self._cache, new_cache
            # indexes are rebuilt from scratch and swapped in atomically
            # with the cache (both under self._lock): a reader never sees
            # a cache/index pair from different generations
            self._ns_index, self._job_index = self.build_indexes(new_cache)
            # diff computed under the lock: new_cache IS the live cache now,
            # and concurrent events mutating it mid-iteration would raise.
            # Dispatch itself happens outside (handlers may re-enter).
            events = [
                ("ADDED", obj, None)
                for key, obj in new_cache.items()
                if key not in old_cache
            ]
            events += [
                ("MODIFIED", obj, old_cache[key])
                for key, obj in new_cache.items()
                if key in old_cache and old_cache[key] != obj
            ]
            vanished = [
                (key, old)
                for key, old in old_cache.items()
                if key not in new_cache
            ]
            for key, old in vanished:
                # snapshot-diff deletions tombstone too (best-effort at the
                # vanished object's last known rv): a pre-gap event for the
                # object still in flight in another notifier thread must
                # not resurrect it after the repair — the same wedge the
                # _on_event DELETED branch guards against
                rv = self._rv_int(old)
                if rv is not None:
                    prev_tomb = self._tombstones.get(key)
                    self._tombstones[key] = (
                        rv if prev_tomb is None else max(rv, prev_tomb)
                    )
                    while len(self._tombstones) > self.MAX_TOMBSTONES:
                        self._tombstones.pop(next(iter(self._tombstones)))
            events += [("DELETED", old, old) for _, old in vanished]
        for event_type, obj, old in events:
            self._dispatch(event_type, obj, old)
        return True

    def _dispatch(
        self, event_type: str, obj: Dict[str, Any], old: Optional[Dict[str, Any]]
    ) -> None:
        for h in self._handlers:
            if event_type == "ADDED" and h.add_func:
                h.add_func(obj)
            elif event_type == "MODIFIED" and h.update_func:
                h.update_func(old if old is not None else obj, obj)
            elif event_type == "DELETED" and h.delete_func:
                h.delete_func(obj)

    def _resync_loop(self) -> None:
        """Periodic resync: re-deliver every cached object as an update with
        old==new (client-go semantics; the reference leans on a forced resync
        for EnableDynamicWorker scaling, controller.go:336)."""
        while not self._stop.wait(self.resync_period):
            self.resync_once()

    def resync_once(self) -> None:
        # a watch-gap repair that failed (apiserver still erroring at
        # relist time) is retried here, so recovery needs no further
        # ERROR event — the periodic resync doubles as the retry loop
        with self._lock:
            needs = self._needs_relist
        if needs:
            self.relist()
        with self._lock:
            snapshot = list(self._cache.values())
        for obj in snapshot:
            for h in self._handlers:
                if h.update_func:
                    h.update_func(obj, obj)

    # ------------------------------------------------------------- cache/test
    def indexer_add(self, obj: Dict[str, Any]) -> None:
        with self._lock:
            self._cache_upsert(objects.key_of(obj), obj)

    def cache_keys(self) -> List[str]:
        with self._lock:
            return list(self._cache)


class Lister:
    """Read-only view over an informer's cache (reference
    pkg/client/listers/tensorflow/v1/tfjob.go).

    `list` is index-accelerated: a namespace narrows the scan to that
    namespace's bucket, and a selector carrying the job-name label
    (GenLabels — the sync hot path's shape) narrows it to the job's own
    O(replicas) objects.  Returned objects are the cache's own unless
    `copy=True`; callers that mutate (the engine's adopt/claim path) must
    ask for copies or they corrupt the cache."""

    def __init__(self, informer: SharedIndexInformer) -> None:
        self._informer = informer

    def synced(self) -> bool:
        """True only when the cache is safe to serve the hot path: it has
        completed its initial list AND no watch-gap repair is pending.  A
        failed relist (apiserver still erroring at repair time) leaves the
        cache knowingly missing a gap until resync retries it — consumers
        must fall back to live LISTs for that window instead of serving
        stale state, which is exactly what the engine's _cached_dependents
        does on False."""
        inf = self._informer
        return inf.has_synced() and not inf._needs_relist

    def get(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        with self._informer._lock:
            return self._informer._cache.get(f"{namespace}/{name}")

    def list(
        self,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
        copy: bool = False,
    ) -> List[Dict[str, Any]]:
        inf = self._informer
        job_name = (selector or {}).get(objects.LABEL_JOB_NAME)
        with inf._lock:
            if namespace is not None and job_name is not None:
                items = list(inf._job_index.get((namespace, job_name), {}).values())
            elif namespace is not None:
                items = list(inf._ns_index.get(namespace, {}).values())
            else:
                items = list(inf._cache.values())
        out = []
        for obj in items:
            # the index guarantees namespace and job-name already; the
            # residual selector keys (group-name, replica-type, ...) still
            # match here — selector_matches over 2-3 keys is cheap
            if namespace is not None and objects.namespace_of(obj) != namespace:
                continue
            if selector and not objects.selector_matches(
                selector, objects.labels_of(obj)
            ):
                continue
            out.append(objects.fast_deepcopy(obj) if copy else obj)
        return out


class SharedInformerFactory:
    """One informer per kind, shared across consumers (reference
    pkg/client/informers/externalversions/factory.go)."""

    def __init__(self, cluster, resync_period: float = 0.0) -> None:
        self.cluster = cluster
        self.resync_period = resync_period
        self._informers: Dict[str, SharedIndexInformer] = {}

    def for_kind(self, kind: str) -> SharedIndexInformer:
        if kind not in self._informers:
            self._informers[kind] = SharedIndexInformer(
                self.cluster, kind, self.resync_period
            )
        return self._informers[kind]

    def start_all(self) -> None:
        for inf in self._informers.values():
            inf.start()

    def stop_all(self) -> None:
        for inf in self._informers.values():
            inf.stop()

    def wait_for_cache_sync(self, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(i.has_synced() for i in self._informers.values()):
                return True
            time.sleep(0.005)
        return False
