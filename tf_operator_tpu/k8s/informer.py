"""Shared informers, listers, and rate-limited workqueues.

The Python equivalent of the reference's generated client machinery
(SURVEY.md §2.5: SharedInformerFactory `pkg/client/informers/
externalversions/factory.go`, listers `pkg/client/listers/tensorflow/v1/
tfjob.go`) plus client-go's workqueue (the legacy controller's hot loop
pops from a rate-limiting queue: reference
pkg/controller.v1/tensorflow/controller.go:230-286).

Design notes (differences from a line-by-line translation, deliberate):
- The cluster store itself (k8s/fake.py FakeCluster) already delivers
  ADDED/MODIFIED/DELETED callbacks, so the informer here is a thin cache +
  handler fan-out + resync layer, not a watch-decoder.
- The queue keeps client-go's exact semantics (dirty/processing sets so an
  item re-added mid-processing is re-delivered exactly once; per-item
  exponential backoff with Forget on success) because the reference's
  correctness depends on them: one worker per job key at a time
  ("syncTFJob is not meant to be invoked concurrently with the same key",
  reference controller.go:299-301).
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.k8s import objects


def capped_exponential(base: float, n: int, cap: float) -> float:
    """base * 2^n clamped to cap, overflow-safe for huge n — THE formula
    behind every backoff ladder in this codebase (workqueue rate limiter,
    watch reconnect, crash-loop restart).  The exponent clamp matters: past
    ~2^60 the product overflows float conversion, and anything that has
    been failing that long is pinned at the cap anyway — found by the
    chaos soak."""
    if base <= 0.0:
        return 0.0
    if n >= 60:
        return cap
    return min(cap, base * (2 ** n))


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff: base * 2^failures, capped.
    (client-go's DefaultControllerRateLimiter core, minus the token bucket —
    the bucket only matters against a real apiserver.)"""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0) -> None:
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return capped_exponential(self.base_delay, n, self.max_delay)

    def forget(self, item: Any) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue:
    """Deduplicating work queue with delayed and rate-limited adds.

    Invariants (client-go workqueue contract):
      - an item is delivered to at most one worker at a time;
      - adding an item already queued is a no-op (dedup);
      - adding an item currently being processed marks it dirty, and it is
        re-queued when the worker calls done();
      - shutdown() wakes all blocked getters, which then receive None.
    """

    def __init__(self, rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None):
        self._cond = threading.Condition()
        self._queue: List[Any] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutdown = False
        self._rate_limiter = rate_limiter or ItemExponentialFailureRateLimiter()
        # delayed adds: heap of (fire_time, seq, item)
        self._heap: List[Tuple[float, int, Any]] = []
        self._seq = 0
        self._timer_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- core
    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Block until an item is available (or shutdown/timeout -> None)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._shutdown:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining if remaining is not None else 0.1)
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._dirty.discard(item)
            self._processing.add(item)
            return item

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty and item not in self._queue:
                self._queue.append(item)
                self._cond.notify()

    # ------------------------------------------------------------- delayed
    def add_after(self, item: Any, delay: float) -> None:
        """Queue `item` after `delay` seconds. The seam the reference's new
        stack broke (FakeWorkQueue.AddAfter is a no-op, reference
        fake_workqueue.go:27) — here it is real and tested."""
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._heap, (time.monotonic() + delay, self._seq, item))
            # the timer thread clears _timer_thread under this lock before it
            # exits, so `is None` is a race-free liveness check (an is_alive()
            # check would miss a thread that decided to exit but hasn't died)
            if self._timer_thread is None:
                self._timer_thread = threading.Thread(
                    target=self._timer_loop, daemon=True
                )
                self._timer_thread.start()
            self._cond.notify()

    def _timer_loop(self) -> None:
        while True:
            with self._cond:
                if self._shutdown or not self._heap:
                    self._timer_thread = None
                    return
                fire_at, _, item = self._heap[0]
                now = time.monotonic()
                if fire_at <= now:
                    heapq.heappop(self._heap)
                    ready = item
                else:
                    self._cond.wait(min(fire_at - now, 0.05))
                    continue
            self.add(ready)

    def add_rate_limited(self, item: Any) -> float:
        """Returns the backoff delay applied, so callers timing queue
        latency can stamp the key's *due* time rather than charging the
        deliberate backoff to the latency histogram."""
        delay = self._rate_limiter.when(item)
        self.add_after(item, delay)
        return delay

    def forget(self, item: Any) -> None:
        self._rate_limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self._rate_limiter.num_requeues(item)

    # ------------------------------------------------------------- lifecycle
    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def pending_delayed(self) -> int:
        with self._cond:
            return len(self._heap)

    def empty(self) -> bool:
        with self._cond:
            return not self._queue and not self._processing

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutdown


# handlers receive the k8s-shaped dict; update handlers receive (old, new)
AddFunc = Callable[[Dict[str, Any]], None]
UpdateFunc = Callable[[Dict[str, Any], Dict[str, Any]], None]
DeleteFunc = Callable[[Dict[str, Any]], None]


class ResourceEventHandler:
    def __init__(
        self,
        add_func: Optional[AddFunc] = None,
        update_func: Optional[UpdateFunc] = None,
        delete_func: Optional[DeleteFunc] = None,
    ) -> None:
        self.add_func = add_func
        self.update_func = update_func
        self.delete_func = delete_func


class SharedIndexInformer:
    """Local cache of one kind + handler fan-out + periodic resync.

    The cache (indexer) is what listers read; tests may also inject fixtures
    directly with `indexer_add` the way the reference's controller tests
    inject into informer indexers (reference job_test.go:40-64)."""

    def __init__(self, cluster, kind: str, resync_period: float = 0.0) -> None:
        self.cluster = cluster
        self.kind = kind
        self.resync_period = resync_period
        self._lock = threading.RLock()
        self._cache: Dict[str, Dict[str, Any]] = {}
        self._handlers: List[ResourceEventHandler] = []
        self._synced = False
        self._stop = threading.Event()
        self._resync_thread: Optional[threading.Thread] = None
        self._needs_relist = False
        # relist vs concurrent-event guard: while a relist's LIST is in
        # flight, deletes AND upserts observed by _on_event are recorded so
        # the stale list snapshot can neither resurrect an object deleted
        # mid-relist nor clobber (and phantom-DELETE) one created/updated
        # mid-relist
        self._relisting = False
        self._relist_deletes: set = set()
        self._relist_upserts: Dict[str, Dict[str, Any]] = {}
        # one relist at a time: the ERROR-dispatch thread and the resync
        # thread's pending-repair retry would otherwise interleave and
        # clobber the tombstone/upsert state above (plain Lock — never
        # taken while holding self._lock, so no ordering cycle)
        self._relist_mutex = threading.Lock()
        cluster.subscribe(kind, self._on_event)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        """List current state into the cache and deliver initial ADDs."""
        initial = self.cluster.list(self.kind)
        with self._lock:
            for obj in initial:
                self._cache[objects.key_of(obj)] = obj
            self._synced = True
        for obj in initial:
            self._dispatch("ADDED", obj, None)
        if self.resync_period > 0 and self._resync_thread is None:
            self._resync_thread = threading.Thread(target=self._resync_loop, daemon=True)
            self._resync_thread.start()

    def stop(self) -> None:
        self._stop.set()

    def has_synced(self) -> bool:
        return self._synced

    # ------------------------------------------------------------- events
    def add_event_handler(self, handler: ResourceEventHandler) -> None:
        self._handlers.append(handler)

    def _on_event(self, event_type: str, obj: Dict[str, Any]) -> None:
        if event_type == "ERROR":
            # the watch layer lost events it cannot replay (410 Gone /
            # stream gap): repair by relisting and diffing, like client-go's
            # Reflector Replace — re-pinning without the diff would hide
            # whatever happened during the gap forever
            self.relist()
            return
        key = objects.key_of(obj)
        old = None
        with self._lock:
            if event_type == "DELETED":
                old = self._cache.pop(key, None)
                if self._relisting:
                    self._relist_deletes.add(key)
                    self._relist_upserts.pop(key, None)
            else:
                old = self._cache.get(key)
                self._cache[key] = obj
                if self._relisting:
                    self._relist_upserts[key] = obj
                    self._relist_deletes.discard(key)
        self._dispatch(event_type, obj, old)

    def relist(self) -> bool:
        """Resync the cache from an authoritative list and dispatch the
        DIFF — new objects as adds, changed as updates, vanished as deletes
        (delete events are exactly what a naive cache reset loses).  On a
        failed list (the apiserver may still be erroring) the repair stays
        pending and resync_once retries it.  Deletes and upserts observed
        concurrently with the LIST win over the (already stale) snapshot.
        Returns True on success."""
        with self._relist_mutex:
            return self._relist_locked()

    def _relist_locked(self) -> bool:
        with self._lock:
            self._relisting = True
            self._relist_deletes = set()
            self._relist_upserts = {}
        try:
            current = self.cluster.list(self.kind)
        except Exception:
            with self._lock:
                self._needs_relist = True
                self._relisting = False
            return False
        with self._lock:
            self._needs_relist = False
            self._relisting = False
            tombstones, self._relist_deletes = self._relist_deletes, set()
            upserts, self._relist_upserts = self._relist_upserts, {}
            new_cache = {
                key: obj
                for obj in current
                if (key := objects.key_of(obj)) not in tombstones
            }
            new_cache.update(upserts)  # live events beat the snapshot
            old_cache, self._cache = self._cache, new_cache
            # diff computed under the lock: new_cache IS the live cache now,
            # and concurrent events mutating it mid-iteration would raise.
            # Dispatch itself happens outside (handlers may re-enter).
            events = [
                ("ADDED", obj, None)
                for key, obj in new_cache.items()
                if key not in old_cache
            ]
            events += [
                ("MODIFIED", obj, old_cache[key])
                for key, obj in new_cache.items()
                if key in old_cache and old_cache[key] != obj
            ]
            events += [
                ("DELETED", old, old)
                for key, old in old_cache.items()
                if key not in new_cache
            ]
        for event_type, obj, old in events:
            self._dispatch(event_type, obj, old)
        return True

    def _dispatch(
        self, event_type: str, obj: Dict[str, Any], old: Optional[Dict[str, Any]]
    ) -> None:
        for h in self._handlers:
            if event_type == "ADDED" and h.add_func:
                h.add_func(obj)
            elif event_type == "MODIFIED" and h.update_func:
                h.update_func(old if old is not None else obj, obj)
            elif event_type == "DELETED" and h.delete_func:
                h.delete_func(obj)

    def _resync_loop(self) -> None:
        """Periodic resync: re-deliver every cached object as an update with
        old==new (client-go semantics; the reference leans on a forced resync
        for EnableDynamicWorker scaling, controller.go:336)."""
        while not self._stop.wait(self.resync_period):
            self.resync_once()

    def resync_once(self) -> None:
        # a watch-gap repair that failed (apiserver still erroring at
        # relist time) is retried here, so recovery needs no further
        # ERROR event — the periodic resync doubles as the retry loop
        with self._lock:
            needs = self._needs_relist
        if needs:
            self.relist()
        with self._lock:
            snapshot = list(self._cache.values())
        for obj in snapshot:
            for h in self._handlers:
                if h.update_func:
                    h.update_func(obj, obj)

    # ------------------------------------------------------------- cache/test
    def indexer_add(self, obj: Dict[str, Any]) -> None:
        with self._lock:
            self._cache[objects.key_of(obj)] = obj

    def cache_keys(self) -> List[str]:
        with self._lock:
            return list(self._cache)


class Lister:
    """Read-only view over an informer's cache (reference
    pkg/client/listers/tensorflow/v1/tfjob.go)."""

    def __init__(self, informer: SharedIndexInformer) -> None:
        self._informer = informer

    def get(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        with self._informer._lock:
            return self._informer._cache.get(f"{namespace}/{name}")

    def list(
        self,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        with self._informer._lock:
            items = list(self._informer._cache.values())
        out = []
        for obj in items:
            if namespace is not None and objects.namespace_of(obj) != namespace:
                continue
            if selector and not objects.selector_matches(
                selector, objects.labels_of(obj)
            ):
                continue
            out.append(obj)
        return out


class SharedInformerFactory:
    """One informer per kind, shared across consumers (reference
    pkg/client/informers/externalversions/factory.go)."""

    def __init__(self, cluster, resync_period: float = 0.0) -> None:
        self.cluster = cluster
        self.resync_period = resync_period
        self._informers: Dict[str, SharedIndexInformer] = {}

    def for_kind(self, kind: str) -> SharedIndexInformer:
        if kind not in self._informers:
            self._informers[kind] = SharedIndexInformer(
                self.cluster, kind, self.resync_period
            )
        return self._informers[kind]

    def start_all(self) -> None:
        for inf in self._informers.values():
            inf.start()

    def stop_all(self) -> None:
        for inf in self._informers.values():
            inf.stop()

    def wait_for_cache_sync(self, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(i.has_synced() for i in self._informers.values()):
                return True
            time.sleep(0.005)
        return False
