"""Kubernetes-shaped object helpers.

Pods and Services are represented as plain nested dicts in standard k8s JSON
shape (the reference manipulates typed Go structs; its legacy informer path
works on Unstructured — see reference pkg/common/util/v1/unstructured/
informer.go:26 — and dicts are the Python-idiomatic unstructured form).
This module holds constructors and accessors so the rest of the codebase
never hand-assembles raw dicts.
"""
from __future__ import annotations

import copy
import time
import uuid
from typing import Any, Dict, List, Optional

# Pod phases (k8s core/v1)
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"
POD_UNKNOWN = "Unknown"

# Label keys — same contract as the reference (kubeflow/common
# JobRoleLabel/ReplicaTypeLabel; see reference tfjob_controller.go:762-767).
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "job-name"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_REPLICA_INDEX = "replica-index"
LABEL_JOB_ROLE = "job-role"
# slice incarnation stamp for whole-slice-restart types: the replica-status
# restart counter at pod creation; a pod whose stamp is behind the counter
# belongs to a torn-down incarnation (no reference counterpart — the
# reference restarts pods individually)
LABEL_RESTART_GENERATION = "restart-generation"

GROUP_NAME = "kubeflow.org"
API_VERSION = GROUP_NAME + "/v1"


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def fast_deepcopy(obj: Any) -> Any:
    """Deep copy for JSON-shaped k8s objects (dict/list/scalar) — ~6× faster
    than copy.deepcopy, which dominates the REST-facade request path at
    O(100)-job scale (every store read/write/notify copies whole objects).
    Non-JSON values fall back to copy.deepcopy so the store stays safe if a
    test smuggles something exotic into an object."""
    t = obj.__class__
    if t is dict:
        return {k: fast_deepcopy(v) for k, v in obj.items()}
    if t is list:
        return [fast_deepcopy(v) for v in obj]
    if t is str or t is int or t is float or t is bool or obj is None:
        return obj
    return copy.deepcopy(obj)


def new_uid() -> str:
    return str(uuid.uuid4())


def make_meta(
    name: str,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    meta: Dict[str, Any] = {"name": name, "namespace": namespace}
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    return meta


def owner_reference(owner: Dict[str, Any], controller: bool = True) -> Dict[str, Any]:
    """Build an ownerReference to `owner` (a k8s-shaped dict with apiVersion,
    kind, metadata.name/.uid). Mirrors GenOwnerReference usage
    (reference pod.go:183)."""
    meta = owner.get("metadata", {})
    return {
        "apiVersion": owner.get("apiVersion", API_VERSION),
        "kind": owner.get("kind", ""),
        "name": meta.get("name", ""),
        "uid": meta.get("uid", ""),
        "controller": controller,
        "blockOwnerDeletion": True,
    }


def get_controller_of(obj: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    for ref in obj.get("metadata", {}).get("ownerReferences", []) or []:
        if ref.get("controller"):
            return ref
    return None


def name_of(obj: Dict[str, Any]) -> str:
    return obj.get("metadata", {}).get("name", "")


# Cluster-scoped kinds key under the empty namespace everywhere (store,
# transport, renderer) — the single source of truth for scoping, so an
# object seeded directly into FakeCluster and one POSTed through the REST
# facade agree on their key.
CLUSTER_SCOPED_KINDS = {
    "Namespace", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleBinding", "PriorityClass", "StorageClass",
    "ValidatingWebhookConfiguration", "MutatingWebhookConfiguration",
    "ClusterIssuer", "Node",
}


def namespace_of(obj: Dict[str, Any]) -> str:
    if obj.get("kind") in CLUSTER_SCOPED_KINDS:
        return ""
    return obj.get("metadata", {}).get("namespace", "default")


def normalize_namespace(kind: str, namespace: Optional[str]) -> Optional[str]:
    """Caller-supplied namespace for a kind: cluster-scoped kinds always
    resolve to the empty namespace regardless of what was passed."""
    return "" if kind in CLUSTER_SCOPED_KINDS else namespace


def uid_of(obj: Dict[str, Any]) -> str:
    return obj.get("metadata", {}).get("uid", "")


def labels_of(obj: Dict[str, Any]) -> Dict[str, str]:
    return obj.get("metadata", {}).get("labels", {}) or {}


def key_of(obj: Dict[str, Any]) -> str:
    """namespace/name key (client-go cache.MetaNamespaceKeyFunc analogue)."""
    return f"{namespace_of(obj)}/{name_of(obj)}"


def pod_phase(pod: Dict[str, Any]) -> str:
    return pod.get("status", {}).get("phase", POD_PENDING)


def pod_restart_generation(pod: Dict[str, Any]) -> "int | None":
    """The whole-slice incarnation the pod was created for.  None when the
    label is absent or malformed: a pre-upgrade (or hand-made) pod counts
    as the CURRENT incarnation — a healthy running slice must never be
    torn down just for missing the stamp."""
    val = labels_of(pod).get(LABEL_RESTART_GENERATION)
    if val is None:
        return None
    try:
        return int(val)
    except ValueError:
        return None


def pod_node(pod: Dict[str, Any]) -> Optional[str]:
    """The node a pod is bound to (spec.nodeName), or None while unbound.
    Written by the scheduler at create time for gang-admitted pods, by
    the chaos kubelet at Running for everything else."""
    return (pod.get("spec") or {}).get("nodeName") or None


def is_pod_active(pod: Dict[str, Any]) -> bool:
    return pod_phase(pod) in (POD_PENDING, POD_RUNNING)


def pod_deleted(pod: Dict[str, Any]) -> bool:
    return bool(pod.get("metadata", {}).get("deletionTimestamp"))


def make_pod(
    name: str,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    template: Optional[Dict[str, Any]] = None,
    phase: str = POD_PENDING,
) -> Dict[str, Any]:
    """Construct a pod dict, optionally from a podTemplateSpec dict
    ({metadata: ..., spec: ...})."""
    template = copy.deepcopy(template) if template else {}
    meta = template.get("metadata", {})
    merged_labels = dict(meta.get("labels", {}) or {})
    if labels:
        merged_labels.update(labels)
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": merged_labels,
            "annotations": dict(meta.get("annotations", {}) or {}),
        },
        "spec": template.get("spec", {}),
        "status": {"phase": phase},
    }
    return pod


def make_service(
    name: str,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    selector: Optional[Dict[str, str]] = None,
    port: int = 0,
    port_name: str = "",
) -> Dict[str, Any]:
    """A headless Service giving the replica a stable DNS name
    (reference: engine ReconcileServices; clusterIP None)."""
    svc: Dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": dict(labels or {}),
        },
        "spec": {
            "clusterIP": "None",
            "selector": dict(selector or labels or {}),
            "ports": [],
        },
    }
    if port:
        svc["spec"]["ports"].append({"name": port_name or "port", "port": port})
    return svc


def containers_of(pod_or_template: Dict[str, Any]) -> List[Dict[str, Any]]:
    return pod_or_template.get("spec", {}).get("containers", []) or []


def find_container(pod_or_template: Dict[str, Any], name: str) -> Optional[Dict[str, Any]]:
    for c in containers_of(pod_or_template):
        if c.get("name") == name:
            return c
    return None


def default_container(
    pod_or_template: Dict[str, Any], name: str
) -> Optional[Dict[str, Any]]:
    """The framework container by name, falling back to container index 0 —
    the single targeting rule shared by port defaulting and resource
    injection (reference defaults.go:38-60 uses the same fallback)."""
    c = find_container(pod_or_template, name)
    if c is not None:
        return c
    containers = containers_of(pod_or_template)
    return containers[0] if containers else None


def find_port(container: Dict[str, Any], port_name: str) -> Optional[int]:
    for p in container.get("ports", []) or []:
        if p.get("name") == port_name:
            return p.get("containerPort")
    return None


def replica_port(
    template: Dict[str, Any], container_name: str, port_name: str, default: int
) -> int:
    """Port of the named port on the framework container, else `default`
    (reference GetPortFromTFJob util.go:29-42 and per-framework copies)."""
    c = find_container(template, container_name)
    if c is not None:
        p = find_port(c, port_name)
        if p:
            return p
    return default


def set_env(container: Dict[str, Any], name: str, value: str) -> None:
    """Idempotently set an env var on a container dict."""
    env = container.setdefault("env", [])
    for e in env:
        if e.get("name") == name:
            e["value"] = value
            return
    env.append({"name": name, "value": value})


def get_env(container: Dict[str, Any], name: str) -> Optional[str]:
    for e in container.get("env", []) or []:
        if e.get("name") == name:
            return e.get("value")
    return None


def selector_matches(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def container_exit_code(pod: Dict[str, Any], container_name: str) -> int:
    """Read the terminated exit code of `container_name` from containerStatuses.
    Returns the 0xbeef sentinel when unavailable — same magic the reference
    uses (reference pod.go:129-138)."""
    for st in pod.get("status", {}).get("containerStatuses", []) or []:
        if st.get("name") == container_name:
            term = (st.get("state") or {}).get("terminated")
            if term is not None and "exitCode" in term:
                return int(term["exitCode"])
    return 0xBEEF
