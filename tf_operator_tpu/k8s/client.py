"""ClusterClient — the real-apiserver backend for the operator.

Presents the exact surface of `k8s.fake.FakeCluster` (create / get / update /
delete / list+selector / subscribe-watch / typed pod+service sugar / event
recording / pod logs) over the Kubernetes REST API, so the engine, manager,
SDK, and informers run unmodified on either backend.  This is the analogue of
the reference's clientset construction (reference
cmd/tf-operator.v1/app/server.go:198-229) plus its typed TFJob client
(reference pkg/client/clientset/versioned/clientset.go) — collapsed into one
unstructured client, which is how the repo's legacy dynamic-informer path
worked anyway (reference pkg/common/util/v1/unstructured/informer.go:26-41).

Transport is pluggable: `HttpTransport` (stdlib http.client + kubeconfig TLS /
token auth — no external kubernetes package needed) for a live cluster, or any
object with the same `request`/`stream` signature for tests.  The test suite
drives ClusterClient against a stub transport replaying real apiserver
behaviors (409 on stale resourceVersion, 404, watch streams with
MODIFIED/DELETED/BOOKMARK, 410 Gone relist) — the achievable equivalent of the
reference's envtest tier (reference
pkg/controller.v1/tensorflow/suite_test.go:50-76).
"""
from __future__ import annotations

import base64
import json
import os
import random
import socket as _socket
import ssl
import tempfile
import threading
import time
from dataclasses import dataclass
from http.client import (
    BadStatusLine,
    HTTPConnection,
    HTTPException,
    HTTPSConnection,
)
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple
from urllib.parse import urlencode, urlsplit

from tf_operator_tpu.engine import metrics
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.informer import capped_exponential
from tf_operator_tpu.k8s.fake import (
    ApiError,
    ConflictError,
    NotFoundError,
    is_retryable_api_error,
    is_transient_api_error,  # noqa: F401 — re-exported: the classification
    # the manager consumes lives conceptually in this layer
)

EventHandler = Callable[[str, Dict[str, Any]], None]


# -------------------------------------------------------------------- retry
@dataclass
class RetryPolicy:
    """Transport retry tuning: exponential backoff with FULL jitter
    (delay ~ U(0, min(max, base * 2^attempt)) — AWS-style, so a fleet of
    operators hammered by the same outage does not reconverge in lockstep),
    bounded by both an attempt budget and a per-request wall-clock deadline.
    A server-provided Retry-After overrides the computed delay."""

    base_delay: float = 0.2
    max_delay: float = 10.0
    max_attempts: int = 6
    deadline: float = 30.0  # per-request budget incl. sleeps, seconds

    def backoff(self, attempt: int, rng: random.Random) -> float:
        return rng.uniform(
            0.0, capped_exponential(self.base_delay, attempt, self.max_delay)
        )


def _retry_after_from(headers: Optional[Dict[str, str]]) -> Optional[float]:
    """Parse a Retry-After header (delta-seconds form only; HTTP-date is
    not worth a date parser here) into seconds."""
    if not headers:
        return None
    for k, v in headers.items():
        if k.lower() == "retry-after":
            try:
                return max(0.0, float(v))
            except (TypeError, ValueError):
                return None
    return None


# --------------------------------------------------------------------- kinds
@dataclass(frozen=True)
class KindInfo:
    """REST coordinates for one kind (the role client-go's RESTMapper plays)."""

    group: str  # "" = core
    version: str
    plural: str
    has_status: bool = False  # status subresource enabled
    cluster_scoped: bool = False  # no /namespaces/{ns}/ path segment

    @property
    def api_prefix(self) -> str:
        if not self.group:
            return f"/api/{self.version}"
        return f"/apis/{self.group}/{self.version}"


# Job CRDs carry the status subresource (manifests/base/crds/*.yaml set
# `subresources: {status: {}}`), so plain PUTs to the main resource drop
# status changes — update() below routes status writes to /status.
_JOB_KINDS = (
    "TFJob", "PyTorchJob", "MXJob", "XGBoostJob", "TPUJob", "TPUServingJob"
)

KIND_REGISTRY: Dict[str, KindInfo] = {
    "Pod": KindInfo("", "v1", "pods"),
    "Service": KindInfo("", "v1", "services"),
    "Event": KindInfo("", "v1", "events"),
    "PodGroup": KindInfo("scheduling.volcano.sh", "v1beta1", "podgroups"),
    # scheduler-plugins coscheduling gang backend: same k8s kind name
    # (PodGroup) in a different API group — registered under a distinct
    # registry key because routing here is by kind string
    "CoschedulingPodGroup": KindInfo(
        "scheduling.x-k8s.io", "v1alpha1", "podgroups"
    ),
    "Lease": KindInfo("coordination.k8s.io", "v1", "leases"),
    # cluster scheduler's slice inventory (engine/scheduler.py): each Node
    # models one TPU slice (chip capacity + accelerator generation)
    "Node": KindInfo("", "v1", "nodes", cluster_scoped=True),
    # kinds the deploy tooling applies (tf_operator_tpu/deploy/cluster.py)
    "Namespace": KindInfo("", "v1", "namespaces", cluster_scoped=True),
    "ServiceAccount": KindInfo("", "v1", "serviceaccounts"),
    "Deployment": KindInfo("apps", "v1", "deployments", has_status=True),
    "CustomResourceDefinition": KindInfo(
        "apiextensions.k8s.io", "v1", "customresourcedefinitions",
        cluster_scoped=True,
    ),
    "ClusterRole": KindInfo(
        "rbac.authorization.k8s.io", "v1", "clusterroles", cluster_scoped=True
    ),
    "ClusterRoleBinding": KindInfo(
        "rbac.authorization.k8s.io", "v1", "clusterrolebindings",
        cluster_scoped=True,
    ),
    **{
        kind: KindInfo(objects.GROUP_NAME, "v1", kind.lower() + "s", has_status=True)
        for kind in _JOB_KINDS
    },
}


# the registry's scoping flags must agree with the store's shared table
# (k8s/objects.py) — divergence would key an object one way in FakeCluster
# and another in REST paths
assert {k for k, i in KIND_REGISTRY.items() if i.cluster_scoped} == (
    objects.CLUSTER_SCOPED_KINDS & set(KIND_REGISTRY)
), "KIND_REGISTRY cluster_scoped flags diverge from objects.CLUSTER_SCOPED_KINDS"


def kind_info(kind: str) -> KindInfo:
    try:
        return KIND_REGISTRY[kind]
    except KeyError:
        raise ApiError(400, f"unregistered kind {kind!r}") from None


def resource_path(
    kind: str, namespace: Optional[str], name: Optional[str] = None,
    subresource: Optional[str] = None,
) -> str:
    info = kind_info(kind)
    path = info.api_prefix
    if namespace and not info.cluster_scoped:
        path += f"/namespaces/{namespace}"
    path += f"/{info.plural}"
    if name:
        path += f"/{name}"
    if subresource:
        path += f"/{subresource}"
    return path


def selector_to_query(selector: Optional[Dict[str, str]]) -> Optional[str]:
    if not selector:
        return None
    return ",".join(f"{k}={v}" for k, v in sorted(selector.items()))


# ----------------------------------------------------------------- kubeconfig
@dataclass
class KubeConfig:
    """The subset of kubeconfig the operator needs: one server + one identity.

    Mirrors what the reference resolves via clientcmd (reference
    server.go:62,97-101 honors KUBECONFIG / --kubeconfig)."""

    server: str
    ca_cert_file: Optional[str] = None
    client_cert_file: Optional[str] = None
    client_key_file: Optional[str] = None
    token: Optional[str] = None
    insecure_skip_tls_verify: bool = False


def _inline_to_file(data_b64: str, suffix: str) -> str:
    """Materialize base64 `*-data` kubeconfig fields (ssl needs file paths)."""
    f = tempfile.NamedTemporaryFile(
        mode="wb", suffix=suffix, prefix="tpuop-kc-", delete=False
    )
    f.write(base64.b64decode(data_b64))
    f.close()
    return f.name


def load_kubeconfig(path: str, context: Optional[str] = None) -> KubeConfig:
    import yaml  # baked in (PyYAML); only needed on the real-cluster path

    with open(path) as fh:
        doc = yaml.safe_load(fh)

    ctx_name = context or doc.get("current-context")
    ctx = next(
        (c["context"] for c in doc.get("contexts", []) if c["name"] == ctx_name),
        None,
    )
    if ctx is None:
        raise ValueError(f"kubeconfig {path}: context {ctx_name!r} not found")
    cluster = next(
        (c["cluster"] for c in doc.get("clusters", []) if c["name"] == ctx["cluster"]),
        None,
    )
    if cluster is None:
        raise ValueError(f"kubeconfig {path}: cluster {ctx['cluster']!r} not found")
    user = next(
        (u["user"] for u in doc.get("users", []) if u["name"] == ctx.get("user")),
        {},
    )

    ca = cluster.get("certificate-authority")
    if not ca and cluster.get("certificate-authority-data"):
        ca = _inline_to_file(cluster["certificate-authority-data"], ".crt")
    cert = user.get("client-certificate")
    if not cert and user.get("client-certificate-data"):
        cert = _inline_to_file(user["client-certificate-data"], ".crt")
    key = user.get("client-key")
    if not key and user.get("client-key-data"):
        key = _inline_to_file(user["client-key-data"], ".key")

    token = user.get("token")
    if not token and user.get("tokenFile"):
        with open(user["tokenFile"]) as fh:
            token = fh.read().strip()

    return KubeConfig(
        server=cluster["server"],
        ca_cert_file=ca,
        client_cert_file=cert,
        client_key_file=key,
        token=token,
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify")),
    )


def in_cluster_config() -> KubeConfig:
    """Pod service-account config (the no---kubeconfig in-cluster path)."""
    sa = "/var/run/secrets/kubernetes.io/serviceaccount"
    host = os.environ["KUBERNETES_SERVICE_HOST"]
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    with open(f"{sa}/token") as fh:
        token = fh.read().strip()
    return KubeConfig(
        server=f"https://{host}:{port}",
        ca_cert_file=f"{sa}/ca.crt",
        token=token,
    )


# ------------------------------------------------------------------ transport
class HttpTransport:
    """Blocking HTTP(S) to the apiserver over a bounded KEEP-ALIVE pool:
    requests check a connection out, ride it, and check it back in, so the
    steady-state cost of an API call is one round trip — not a TCP (and
    TLS) handshake plus a round trip.  Watch streams never touch the pool:
    each `stream()` owns a private connection for its whole life (client-go
    pins one connection per watch the same way) and its cancel hook closes
    that socket.

    Failure containment: any transport error — connection reset, a
    mid-response drop, a `FaultInjector`-style storm — RETIRES the socket
    it happened on.  A poisoned connection must never be handed to the
    next request; the next checkout dials fresh.  An IDEMPOTENT request
    (GET/PUT/DELETE) that dies on a REUSED socket before any response
    bytes arrive is replayed once on a fresh connection: the
    overwhelmingly likely cause is the server having closed the idle
    keep-alive socket between requests (urllib3 replays exactly this
    case), and without the replay pooling would *introduce* spurious
    failures the one-connection-per-request transport never had.  POST is
    never transport-replayed (the reconcile level is the idempotent
    replay — PR 3 invariant), and nothing is replayed once the response
    status line has arrived: the server processed that request.

    `tpu_operator_transport_connections_created_total` /
    `..._reused_total` make the reuse ratio observable: a reconcile burst
    in steady state should create at most `pool_size` connections while
    the reused counter tracks request volume."""

    def __init__(
        self, config: KubeConfig, timeout: float = 30.0, pool_size: int = 8
    ) -> None:
        self.config = config
        self.timeout = timeout
        self.pool_size = max(1, int(pool_size))
        u = urlsplit(config.server)
        self._https = u.scheme == "https"
        self._host = u.hostname or "localhost"
        self._port = u.port or (443 if self._https else 80)
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self._https:
            ctx = ssl.create_default_context(cafile=config.ca_cert_file)
            if config.insecure_skip_tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if config.client_cert_file:
                ctx.load_cert_chain(
                    config.client_cert_file, config.client_key_file
                )
            self._ssl_ctx = ctx
        self._pool_lock = threading.Lock()
        self._idle: List[Any] = []  # LIFO: most-recently-used first
        self._closed = False
        # bounds CONCURRENT request connections (idle + checked out) at
        # pool_size: parallel callers beyond the bound wait for a checkin
        # rather than dialing an unbounded herd at the apiserver
        self._slots = threading.BoundedSemaphore(self.pool_size)

    def _connect(self, timeout: Optional[float]):
        metrics.TRANSPORT_CONNECTIONS_CREATED.inc()
        if self._https:
            return HTTPSConnection(
                self._host, self._port, timeout=timeout, context=self._ssl_ctx
            )
        return HTTPConnection(self._host, self._port, timeout=timeout)

    # ------------------------------------------------------------- pool
    def _checkout(self) -> Tuple[Any, bool]:
        """-> (connection, reused).  Blocks while pool_size connections are
        already in flight; LIFO reuse keeps the warmest socket busiest so
        idle ones age out server-side first."""
        self._slots.acquire()
        with self._pool_lock:
            if self._idle:
                metrics.TRANSPORT_CONNECTIONS_REUSED.inc()
                return self._idle.pop(), True
        return self._connect(self.timeout), False

    def _checkin(self, conn) -> None:
        with self._pool_lock:
            if not self._closed:
                self._idle.append(conn)
                conn = None
        if conn is not None:  # transport closed while this request flew
            conn.close()
        self._slots.release()

    def _retire(self, conn) -> None:
        """Errored (or server-closed) socket: close it and free the slot —
        never back into the pool."""
        try:
            conn.close()
        except Exception:
            pass
        self._slots.release()

    def close(self) -> None:
        """Drop all idle pooled connections; in-flight ones close on their
        request's retire/checkin."""
        with self._pool_lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for conn in idle:
            try:
                conn.close()
            except Exception:
                pass

    def _headers(self, has_body: bool) -> Dict[str, str]:
        h = {"Accept": "application/json"}
        if has_body:
            h["Content-Type"] = "application/json"
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        return h

    def request(
        self,
        method: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        body: Optional[Dict[str, Any]] = None,
    ) -> Tuple[int, Any, Dict[str, str]]:
        """One apiserver round trip -> (status, decoded JSON | raw str,
        response headers).  The headers carry Retry-After on 429/503, which
        the client's retry layer honors; transports that predate the
        3-tuple (test stubs) may still return 2-tuples — consumers unpack
        defensively."""
        if query:
            path = f"{path}?{urlencode(query)}"
        payload = json.dumps(body).encode() if body is not None else None
        while True:
            conn, reused = self._checkout()
            try:
                conn.request(
                    method, path, body=payload,
                    headers=self._headers(body is not None),
                )
                resp = conn.getresponse()
            except (HTTPException, OSError) as e:
                self._retire(conn)
                # Stale keep-alive: the server closed this idle socket
                # between requests, so nothing of the request was processed
                # — replay once on a fresh connection (a fresh-connection
                # failure raises: reused is False).  ONLY idempotent verbs:
                # a POST that died here *probably* never reached the
                # server, but "probably" is not the transport's call to
                # make — PR 3's invariant stands (POST is never
                # transport-replayed; the reconcile level is the
                # idempotent replay), so a stale-socket POST surfaces as a
                # retryable connection error instead.
                if (
                    reused
                    and method in ("GET", "PUT", "DELETE")
                    and isinstance(
                        e, (BadStatusLine, ConnectionError, ssl.SSLEOFError)
                    )
                ):
                    continue
                raise
            except Exception:
                self._retire(conn)
                raise
            try:
                raw = resp.read()
            except Exception:
                # the status line arrived, so the server processed the
                # request: a mid-body drop retires the socket but must
                # NEVER replay — the write may have committed
                self._retire(conn)
                raise
            headers = dict(resp.headers.items())
            # reuse only when the response says the connection survives
            # (HTTP/1.1 keep-alive with sound framing); a close-framed or
            # errored response retires the socket
            if resp.will_close or not resp.isclosed():
                self._retire(conn)
            else:
                self._checkin(conn)
            ctype = resp.headers.get("Content-Type", "")
            if "json" in ctype:
                return resp.status, json.loads(raw) if raw else None, headers
            return resp.status, raw.decode(errors="replace"), headers

    def stream(
        self,
        path: str,
        query: Optional[Dict[str, str]] = None,
        cancel: Optional[List[Callable[[], None]]] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Long-poll watch: yields decoded watch events line by line.  The
        connection stays open until the server closes it or the consumer
        abandons the generator.  A callable appended to `cancel` (if given)
        aborts the blocked read from another thread — without it, a quiet
        watch would pin its thread and socket forever after close()."""
        if query:
            path = f"{path}?{urlencode(query)}"
        # connect + register the cancel hook EAGERLY (not inside the
        # generator): the consumer snapshots `cancel` before first next(),
        # and a lazily-registered hook would be invisible to it.  The
        # watch's connection is PRIVATE — it never comes from or returns
        # to the request pool: an unbounded stream would otherwise pin a
        # pool slot for its whole life and starve request traffic.
        conn = self._connect(None)  # watches are long-lived: no read timeout
        # connect NOW and pin the raw socket: a close-framed (Connection:
        # close) response makes http.client detach `conn.sock` when the
        # response is created, so a late getattr would find None and the
        # cancel hook would wake nobody
        conn.connect()
        sock = conn.sock

        def _cancel() -> None:
            # shutdown() BEFORE close(): close() only drops the fd refcount
            # and does not wake a thread parked in recv() on a quiet watch
            # — shutdown() does, and the reader then sees EOF and exits
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except Exception:
                pass

        if cancel is not None:
            cancel.append(_cancel)

        def _events() -> Iterator[Dict[str, Any]]:
            try:
                conn.request("GET", path, headers=self._headers(False))
                resp = conn.getresponse()
                if resp.status != 200:
                    raw = resp.read()
                    raise ApiError(resp.status, raw.decode(errors="replace"))
                buf = b""
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        return
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if line.strip():
                            yield json.loads(line)
            finally:
                conn.close()

        return _events()


# --------------------------------------------------------------------- client
def _error_for(
    status: int, body: Any, context: str,
    headers: Optional[Dict[str, str]] = None,
) -> ApiError:
    message = body.get("message", str(body)) if isinstance(body, dict) else str(body)
    if status == 404:
        return NotFoundError(f"{context}: {message}")
    if status == 409:
        return ConflictError(f"{context}: {message}")
    return ApiError(
        status, f"{context}: {message}", retry_after=_retry_after_from(headers)
    )


def _unpack(res) -> Tuple[int, Any, Optional[Dict[str, str]]]:
    """Accept both transport reply shapes: (status, body) from legacy stubs
    and (status, body, headers) from HttpTransport."""
    status, body = res[0], res[1]
    headers = res[2] if len(res) > 2 and isinstance(res[2], dict) else None
    return status, body, headers


class _WatchLoop:
    """One background list-watch per kind: list to pin a resourceVersion,
    stream from it, fan events out to handlers; on 410 Gone (or any stream
    loss) RELIST AND DIFF so no event is ever silently dropped.  This is the
    client-go Reflector reduced to what the informers need: FakeCluster's
    subscribe never loses events, and every consumer is written against that
    lossless contract, so the live client must repair watch gaps itself —
    a relist that only re-pins the resourceVersion would permanently hide
    whatever happened during the gap.  The repair diff needs a memory of what
    has been delivered: `_known` maps object key -> resourceVersion for the
    watched kind (bounded by the number of live objects)."""

    def __init__(
        self, client: "ClusterClient", kind: str, first_handler: EventHandler
    ) -> None:
        self.client = client
        self.kind = kind
        # registered before the thread starts: an immediately-chatty stream
        # must not dispatch into an empty handler list
        self.handlers: List[EventHandler] = [first_handler]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._cancels: List[Callable[[], None]] = []
        self._known: Dict[str, str] = {}
        # Pin the start state SYNCHRONOUSLY: subscribers (informers) list
        # their initial state right after subscribe() returns, and every
        # change after their list must reach the watch.  Pinning lazily in
        # the thread would open a gap between the subscriber's list and the
        # watch's own, silently dropping the events in between.
        try:
            self._initial_rv: Optional[str] = self._seed()
        except Exception:
            self._initial_rv = None  # thread will retry the list itself
        self._thread = threading.Thread(
            target=self._run, name=f"watch-{kind}", daemon=True
        )
        self._thread.start()

    def add(self, handler: EventHandler) -> None:
        with self._lock:
            self.handlers.append(handler)

    def remove(self, handler: EventHandler) -> bool:
        """Returns True when no handlers remain (caller may drop the loop)."""
        with self._lock:
            try:
                self.handlers.remove(handler)
            except ValueError:
                pass
            return not self.handlers

    def stop(self) -> None:
        self._stop.set()
        # abort any blocked stream read — a quiet watch otherwise parks the
        # thread (and its connection) on a read that never returns
        with self._lock:
            cancels, self._cancels = self._cancels, []
        for cancel in cancels:
            try:
                cancel()
            except Exception:
                pass

    def _dispatch(self, event_type: str, obj: Dict[str, Any]) -> None:
        key = objects.key_of(obj)
        rv = (obj.get("metadata") or {}).get("resourceVersion", "")
        # dedup against delivered state so watch restarts and relist repairs
        # are invisible to subscribers (at-most-once per distinct change)
        if event_type == "DELETED":
            if self._known.pop(key, None) is None:
                return  # already reported gone (e.g. by a relist diff)
        else:
            if self._known.get(key) == rv:
                return  # replayed event for a change already delivered
            self._known[key] = rv
        with self._lock:
            handlers = list(self.handlers)
        for h in handlers:
            # per-handler copy, matching FakeCluster._notify: a handler that
            # mutates its view must not corrupt another's (or the stream's)
            h(event_type, objects.fast_deepcopy(obj))

    def _list(self) -> Tuple[str, List[Dict[str, Any]]]:
        # watch (re)seeds and gap-repair relists are real LIST round trips:
        # they book under the same {verb=list} series, which is exactly why
        # the steady-state zero-LIST assertion holds — no restarts, no lists
        metrics.API_REQUESTS.inc({"verb": "list", "kind": self.kind})
        status, body, headers = _unpack(
            self.client.transport.request(
                "GET", resource_path(self.kind, self.client.namespace or None)
            )
        )
        if status != 200:
            raise _error_for(status, body, f"watch-list {self.kind}", headers)
        items = body.get("items", []) or []
        for item in items:
            item.setdefault("kind", self.kind)
        return (body.get("metadata") or {}).get("resourceVersion", "0"), items

    def _seed(self) -> str:
        """Initial pin: remember current objects WITHOUT dispatching (the
        subscriber does its own initial list)."""
        rv, items = self._list()
        for item in items:
            self._known[objects.key_of(item)] = (
                item.get("metadata") or {}
            ).get("resourceVersion", "")
        return rv

    def _relist(self) -> str:
        """Gap repair: relist and dispatch the DIFF against what was already
        delivered — changed/new objects as MODIFIED/ADDED, vanished ones as
        DELETED — so subscribers converge despite the lost stream."""
        rv, items = self._list()
        seen = set()
        for item in items:
            key = objects.key_of(item)
            seen.add(key)
            item_rv = (item.get("metadata") or {}).get("resourceVersion", "")
            prior = self._known.get(key)
            if prior is None:
                self._dispatch("ADDED", item)
            elif prior != item_rv:
                self._dispatch("MODIFIED", item)
        for key in [k for k in self._known if k not in seen]:
            ns, _, name = key.partition("/")
            self._dispatch(
                "DELETED",
                {
                    "kind": self.kind,
                    "metadata": {"namespace": ns, "name": name,
                                 "resourceVersion": rv},
                },
            )
        return rv

    def _reconnect_wait(self, failures: int) -> None:
        """Exponential reconnect backoff with jitter, capped — a flat
        cadence would turn an apiserver outage into a synchronized
        thundering herd of relists the moment it heals."""
        policy = self.client.retry
        cap = capped_exponential(max(policy.base_delay, 0.2), failures, 30.0)
        self._stop.wait(self.client._rng.uniform(cap / 2.0, cap))

    def _run(self) -> None:
        rv: Optional[str] = self._initial_rv
        seeded = rv is not None
        failures = 0
        last_failure = 0.0

        def ratchet() -> None:
            """Count a reconnect failure; isolated hiccups hours apart on a
            QUIET kind (no events ever flow to reset the counter) must not
            ratchet the backoff to its cap forever — the ladder restarts
            when the previous failure is old news."""
            nonlocal failures, last_failure
            now = time.monotonic()
            if now - last_failure > 300.0:
                failures = 0
            last_failure = now
            failures += 1

        while not self._stop.is_set():
            try:
                if rv is None:
                    rv = self._relist() if seeded else self._seed()
                    seeded = True
                query = {
                    "watch": "true",
                    "resourceVersion": rv,
                    "allowWatchBookmarks": "true",
                }
                path = resource_path(self.kind, self.client.namespace or None)
                cancel_box: List[Callable[[], None]] = []
                stream = self.client.transport.stream(
                    path, query, cancel=cancel_box
                )
                with self._lock:
                    self._cancels.extend(cancel_box)
                for event in stream:
                    if self._stop.is_set():
                        return
                    etype = event.get("type")
                    obj = event.get("object") or {}
                    if etype == "BOOKMARK":
                        rv = (obj.get("metadata") or {}).get("resourceVersion", rv)
                        continue
                    if etype == "ERROR":
                        # typically 410 Gone: our resourceVersion expired.
                        # Backs off like the exception paths: churn can
                        # expire the rv faster than we re-watch, and an
                        # unthrottled ERROR->relist cycle is a LIST storm
                        # against an already-struggling apiserver.
                        rv = None
                        metrics.WATCH_RESTARTS.inc(
                            {"kind": self.kind, "reason": "gone"}
                        )
                        ratchet()
                        gone_backoff = True
                        break
                    new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if new_rv:
                        rv = new_rv
                    # NOTE: a delivered event does NOT reset the failure
                    # ladder — under rv-churn every cycle delivers a few
                    # events before its 410, and a per-event reset would
                    # pin the backoff at its floor (ratchet()'s 300s rule
                    # is what forgives old failures)
                    if etype in ("ADDED", "MODIFIED", "DELETED"):
                        self._dispatch(etype, obj)
                else:
                    gone_backoff = False
                if gone_backoff:
                    # drop the dead stream's connection BEFORE backing off —
                    # sleeping inside the loop would pin the apiserver's
                    # watch slot for the whole wait
                    close = getattr(stream, "close", None)
                    if close is not None:
                        close()
                    self._reconnect_wait(failures)
            except ApiError as e:
                if e.code == 410:
                    rv = None  # expired: relist + diff
                metrics.WATCH_RESTARTS.inc({
                    "kind": self.kind,
                    "reason": "gone" if e.code == 410 else "error",
                })
                ratchet()
                self._reconnect_wait(failures)
            except Exception:
                # transport hiccough — reconnect from last good rv; if the
                # stream constructor/protocol lost events, the next 410 (or
                # explicit rv reset) repairs via _relist
                metrics.WATCH_RESTARTS.inc(
                    {"kind": self.kind, "reason": "error"}
                )
                ratchet()
                self._reconnect_wait(failures)
            finally:
                with self._lock:
                    self._cancels.clear()


class ClusterClient:
    """Real-apiserver implementation of the FakeCluster surface.

    `namespace` scopes list/watch the way the reference's filtered informer
    factory does (reference server.go:129, KUBEFLOW_NAMESPACE scoping);
    empty string = all namespaces."""

    def __init__(
        self,
        transport,
        namespace: str = "",
        retry: Optional[RetryPolicy] = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.transport = transport
        self.namespace = namespace
        self.retry = retry or RetryPolicy()
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._watches: Dict[str, _WatchLoop] = {}
        self._watch_lock = threading.Lock()

    # ------------------------------------------------------------- transport
    def _request(
        self,
        method: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        body: Optional[Dict[str, Any]] = None,
        *,
        ok: Tuple[int, ...] = (200,),
        context: str = "",
        retries: bool = True,
        replayed_404_ok: bool = False,
    ) -> Any:
        """One logical apiserver call with the retry layer applied: retryable
        failures (429 honoring Retry-After, 5xx, connection resets) are
        replayed with full-jitter exponential backoff until the policy's
        attempt budget or per-request deadline runs out; terminal answers
        (404/409/422...) surface immediately with FakeCluster-identical
        exception types."""
        policy = self.retry
        give_up_at = time.monotonic() + policy.deadline
        attempt = 0
        while True:
            err: BaseException
            try:
                status, rbody, headers = _unpack(
                    self.transport.request(method, path, query=query, body=body)
                )
            except Exception as e:  # noqa: BLE001 — classified below
                if not retries or not is_retryable_api_error(e):
                    raise
                err = e
            else:
                if status in ok:
                    return rbody
                if status == 404 and attempt > 0 and replayed_404_ok:
                    # a 404 on a REPLAY means the first attempt committed
                    # before its reply was lost — for DELETE that is
                    # success, not an error (client-go convention); a
                    # first-attempt 404 still surfaces normally
                    return rbody
                err = _error_for(status, rbody, context, headers)
                if not retries or not is_retryable_api_error(err):
                    raise err
            delay = getattr(err, "retry_after", None)
            if delay is None:
                delay = policy.backoff(attempt, self._rng)
            attempt += 1
            if attempt >= policy.max_attempts or (
                time.monotonic() + delay > give_up_at
            ):
                raise err
            metrics.API_RETRIES.inc(
                {"reason": str(getattr(err, "code", "reset"))}
            )
            self._sleep(delay)

    @classmethod
    def from_kubeconfig(
        cls, path: str = "", namespace: str = "", context: Optional[str] = None
    ) -> "ClusterClient":
        if path:
            cfg = load_kubeconfig(path, context)
        elif os.environ.get("KUBECONFIG"):
            cfg = load_kubeconfig(os.environ["KUBECONFIG"], context)
        else:
            cfg = in_cluster_config()
        return cls(HttpTransport(cfg), namespace=namespace)

    # ------------------------------------------------------------- watches
    def subscribe(self, kind: str, handler: EventHandler) -> None:
        with self._watch_lock:
            loop = self._watches.get(kind)
            if loop is None:
                self._watches[kind] = _WatchLoop(self, kind, handler)
            else:
                loop.add(handler)

    def unsubscribe(self, kind: str, handler: EventHandler) -> None:
        with self._watch_lock:
            loop = self._watches.get(kind)
            if loop and loop.remove(handler):
                loop.stop()
                del self._watches[kind]

    def close(self) -> None:
        with self._watch_lock:
            for loop in self._watches.values():
                loop.stop()
            self._watches.clear()

    # ------------------------------------------------------------- generic
    @staticmethod
    def _observe(verb: str, kind: str) -> None:
        """One logical request = one tpu_operator_api_requests_total tick
        (transport replays are counted separately by _api_request_retries)."""
        metrics.API_REQUESTS.inc({"verb": verb, "kind": kind})

    def create(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        self._observe("create", kind)
        # POST is NOT transport-retried (client-go does the same): the first
        # attempt may have committed server-side before the reply was lost,
        # and a blind replay turns success into 409 AlreadyExists.  The safe
        # replay is the RECONCILE level — the manager requeues the
        # transient error and the next sync re-lists and creates only what
        # is actually missing.
        ns = objects.namespace_of(obj)
        return self._request(
            "POST", resource_path(kind, ns), body=obj,
            ok=(200, 201), context=f"create {kind} {objects.key_of(obj)}",
            retries=False,
        )

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        self._observe("get", kind)
        return self._request(
            "GET", resource_path(kind, namespace, name),
            context=f"get {kind} {namespace}/{name}",
        )

    def update(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        """PUT the main resource; for kinds with a status subresource also PUT
        /status (the apiserver drops status changes on main-resource writes
        and vice versa — one FakeCluster.update equals up to two REST calls).
        Stale resourceVersion surfaces as ConflictError, same as the fake."""
        self._observe("update", kind)
        ns, name = objects.namespace_of(obj), objects.name_of(obj)
        context = f"update {kind} {ns}/{name}"
        body = self._request(
            "PUT", resource_path(kind, ns, name), body=obj, context=context
        )
        info = kind_info(kind)
        if info.has_status and "status" in obj:
            # carry the RV the main PUT returned so the status write is not
            # spuriously stale
            staged = dict(obj)
            staged["metadata"] = dict(body.get("metadata", obj.get("metadata", {})))
            return self._request(
                "PUT", resource_path(kind, ns, name, "status"), body=staged,
                context=context + " (status)",
            )
        return body

    def update_status(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        """Status-subresource write in ONE PUT: the engine's hot-path status
        write-back sends the object it already holds (rv rides along for the
        conflict check) straight to /status — no GET-before-update, and none
        of update()'s main-resource PUT whose spec bytes the apiserver would
        discard anyway.  Kinds without a status subresource fall back to a
        plain update."""
        info = kind_info(kind)
        if not info.has_status:
            return self.update(kind, obj)
        self._observe("update_status", kind)
        ns, name = objects.namespace_of(obj), objects.name_of(obj)
        return self._request(
            "PUT", resource_path(kind, ns, name, "status"), body=obj,
            context=f"update {kind} {ns}/{name} (status)",
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._observe("delete", kind)
        self._request(
            "DELETE", resource_path(kind, namespace, name),
            ok=(200, 202), context=f"delete {kind} {namespace}/{name}",
            replayed_404_ok=True,
        )

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        self._observe("list", kind)
        ns = namespace if namespace is not None else (self.namespace or None)
        query: Dict[str, str] = {}
        sel = selector_to_query(selector)
        if sel:
            query["labelSelector"] = sel
        body = self._request(
            "GET", resource_path(kind, ns), query=query or None,
            context=f"list {kind}",
        )
        items = body.get("items", []) or []
        # list responses strip apiVersion/kind from items; restore kind so
        # downstream key/kind logic matches watch-delivered objects
        for item in items:
            item.setdefault("kind", kind)
        return items

    # ------------------------------------------------------------- typed sugar
    def create_pod(self, pod: Dict[str, Any]) -> Dict[str, Any]:
        return self.create("Pod", pod)

    def get_pod(self, namespace: str, name: str) -> Dict[str, Any]:
        return self.get("Pod", namespace, name)

    def update_pod(self, pod: Dict[str, Any]) -> Dict[str, Any]:
        return self.update("Pod", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.delete("Pod", namespace, name)

    def list_pods(self, namespace=None, selector=None) -> List[Dict[str, Any]]:
        return self.list("Pod", namespace, selector)

    def create_service(self, svc: Dict[str, Any]) -> Dict[str, Any]:
        return self.create("Service", svc)

    def delete_service(self, namespace: str, name: str) -> None:
        self.delete("Service", namespace, name)

    def list_services(self, namespace=None, selector=None) -> List[Dict[str, Any]]:
        return self.list("Service", namespace, selector)

    # ------------------------------------------------------------- pod logs
    def read_pod_log(self, namespace: str, name: str) -> str:
        body = self._request(
            "GET", resource_path("Pod", namespace, name, "log"),
            context=f"logs {namespace}/{name}",
        )
        return body if isinstance(body, str) else json.dumps(body)

    # ------------------------------------------------------------- events
    def record_event(
        self,
        obj: Dict[str, Any],
        event_type: str,
        reason: str,
        message: str,
    ) -> None:
        """POST a core/v1 Event (reference record.EventRecorder analogue —
        SURVEY.md §5.5). Event failures are swallowed — and NOT retried:
        observability must never fail a reconcile, and during an apiserver
        outage a retrying event post would stall the very teardown/restart
        work the event describes."""
        ns = objects.namespace_of(obj)
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "generateName": f"{objects.name_of(obj)}.",
                "namespace": ns,
            },
            "type": event_type,
            "reason": reason,
            "message": message,
            "involvedObject": {
                "kind": obj.get("kind", ""),
                "name": objects.name_of(obj),
                "namespace": ns,
                "uid": objects.uid_of(obj),
            },
            "firstTimestamp": objects.now_iso(),
            "lastTimestamp": objects.now_iso(),
            "count": 1,
            "source": {"component": "tpu-operator"},
        }
        try:
            self._request(
                "POST", resource_path("Event", ns), body=event,
                ok=(200, 201), context="record event", retries=False,
            )
        except (ApiError, OSError):
            pass

    def events_for(
        self,
        name: str,
        event_type: Optional[str] = None,
        namespace: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        # a namespace argument also scopes the LIST itself, so a
        # namespace-restricted RBAC principal can still read its events
        out = []
        for e in self.list("Event", namespace=namespace or self.namespace or None):
            obj = e.get("involvedObject") or {}
            if obj.get("name") != name:
                continue
            if event_type is not None and e.get("type") != event_type:
                continue
            if namespace is not None and obj.get("namespace") != namespace:
                continue
            out.append(e)
        return out
