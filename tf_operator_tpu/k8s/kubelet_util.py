"""Shared kubelet-semantics helpers for the two pod materializers —
e2e/kubelet.py (in-process test servers) and runtime/local.py (real
subprocesses). Both must agree on restart-policy decisions, pod status
shapes, and the conflict-retrying status write; keeping those here means
a semantics fix cannot silently apply to only one simulator."""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.fake import ApiError, ConflictError, NotFoundError


def should_restart(policy: str, exit_code: int) -> bool:
    """Kubelet restart decision: Always restarts; OnFailure restarts on
    non-zero; Never/ExitCode go terminal (the operator owns ExitCode —
    reference pod.go:321-328 forces Never on the pod)."""
    return policy == "Always" or (policy == "OnFailure" and exit_code != 0)


def write_pod_status(cluster, namespace: str, name: str,
                     mutate: Callable, retries: int = 5) -> bool:
    """Re-get + retry on write conflicts, like the real kubelet's status
    manager — other writers (controller adoption, tests) race on pods."""
    for _ in range(retries):
        try:
            pod = cluster.get_pod(namespace, name)
            mutate(pod)
            cluster.update_pod(pod)
            return True
        except ConflictError:
            time.sleep(0.01)
            continue
        except (NotFoundError, ApiError):
            return False
    return False


def running_status(container_name: str, restart_count: int,
                   last_exit_code: Optional[int] = None) -> Dict:
    status = {
        "name": container_name,
        "state": {"running": {}},
        "restartCount": restart_count,
    }
    if last_exit_code is not None:
        status["lastState"] = {"terminated": {"exitCode": last_exit_code}}
    return status


def mark_running(pod, container_name: str, restart_count: int,
                 pod_ip: str = "127.0.0.1") -> None:
    pod["status"]["phase"] = objects.POD_RUNNING
    pod["status"]["podIP"] = pod_ip
    pod["status"]["containerStatuses"] = [
        running_status(container_name, restart_count)
    ]


def mark_restarting(pod, container_name: str, restart_count: int,
                    exit_code: int) -> None:
    pod["status"]["containerStatuses"] = [
        running_status(container_name, restart_count, last_exit_code=exit_code)
    ]


def mark_terminal(pod, container_name: str, exit_code: int,
                  restart_count: int) -> None:
    pod["status"]["phase"] = (
        objects.POD_SUCCEEDED if exit_code == 0 else objects.POD_FAILED
    )
    pod["status"]["containerStatuses"] = [{
        "name": container_name,
        "state": {"terminated": {"exitCode": exit_code}},
        "restartCount": restart_count,
    }]
