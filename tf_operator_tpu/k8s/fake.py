"""FakeCluster — an in-memory Kubernetes-state double.

Serves two roles:
  1. The test substrate: the reference tests controllers by injecting fixture
     pods/services straight into informer indexers (reference
     pkg/controller.v1/tensorflow/job_test.go:40-64, testutil/pod.go:57-97);
     FakeCluster is the Python equivalent.
  2. The ClusterClient interface the engine is written against; the real
     apiserver-backed client (k8s/client.py) implements the same surface, so
     the engine is oblivious to which one it runs on.

Event subscription gives informer-style add/update/delete notifications used
by expectation accounting (reference pkg/common/util/reconciler.go:38-157).
"""
from __future__ import annotations

import fnmatch
import ssl
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.k8s import objects


class ApiError(Exception):
    def __init__(self, code: int, message: str, retry_after: Optional[float] = None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        # server-suggested backoff (Retry-After header on 429/503); honored
        # by the retry layer in k8s/client.py over its computed backoff
        self.retry_after = retry_after


class NotFoundError(ApiError):
    def __init__(self, message: str = "not found"):
        super().__init__(404, message)


class ConflictError(ApiError):
    def __init__(self, message: str = "conflict"):
        super().__init__(409, message)


class StaleFencingTokenError(ApiError):
    """A status write stamped with a fencing token older than the owning
    shard slot's current lease generation — the zombie-shard write barrier
    (engine/sharding.py).  403, not 409: a conflict invites re-read-and-
    retry, but a stale token means the writer is no longer the owner and
    replaying the write with the same token can never succeed."""

    def __init__(self, message: str = "stale fencing token"):
        super().__init__(403, message)


# HTTP statuses worth retrying at the transport level: throttling, server
# faults, and timeouts.  Everything else 4xx is a terminal answer — the
# request itself is wrong and replaying it cannot help.
RETRYABLE_HTTP_CODES = frozenset({408, 429, 500, 502, 503, 504})


def is_retryable_api_error(exc: BaseException) -> bool:
    """Transport-level classification: True for errors a blind replay of the
    same request may cure (throttling, apiserver 5xx, dropped connections).
    404/409/422-class answers are terminal here — 409 in particular must
    NOT be replayed verbatim (the write is stale; the caller needs a fresh
    read first).  Deliberately NOT every OSError: a bad CA bundle or a
    missing cert file (SSLCertVerificationError, FileNotFoundError) is a
    permanent misconfiguration that retrying can only disguise as an
    outage — but a TLS stream dropped mid-read (SSLEOFError and friends,
    OSError yet not ConnectionError) is exactly an outage and must
    retry."""
    if isinstance(exc, ApiError):
        return exc.code in RETRYABLE_HTTP_CODES
    if isinstance(exc, ssl.SSLCertVerificationError):
        return False
    if isinstance(exc, ssl.SSLError):
        return True
    return isinstance(exc, (ConnectionError, TimeoutError))


def is_transient_api_error(exc: BaseException) -> bool:
    """Workqueue-level classification: everything retryable at the transport
    PLUS optimistic-concurrency conflicts, which a *fresh reconcile* (re-read,
    recompute, re-write) cures even though a verbatim replay would not.
    Errors in this class should be requeued with backoff indefinitely rather
    than spending the bounded reconcile-retry budget."""
    return is_retryable_api_error(exc) or isinstance(exc, ConflictError)


EventHandler = Callable[[str, Dict[str, Any]], None]  # (event_type, obj)


_METRICS = None


def _observe_api_request(verb: str, kind: str) -> None:
    """tpu_operator_api_requests_total{verb,kind} — the per-call tally the
    'zero steady-state LISTs' tests and the scale bench read.  The metrics
    module is imported lazily: engine/__init__ imports the controller which
    imports this module, so a top-level import here would be a cycle."""
    global _METRICS
    if _METRICS is None:
        from tf_operator_tpu.engine import metrics as _m
        _METRICS = _m
    _METRICS.API_REQUESTS.inc({"verb": verb, "kind": kind})


class FakeCluster:
    """In-memory object store: pods, services, podgroups, and job CRs
    (stored unstructured, keyed by kind).

    `gc=True` (default) emulates the k8s garbage collector synchronously:
    deleting an owner reaps its dependents, and a dependent created for an
    already-dead owner is reaped on arrival. Pass gc=False to simulate GC
    lag windows (e.g. the stale-incarnation adoption races the controller
    must survive on its own)."""

    def __init__(self, gc: bool = True) -> None:
        self.gc = gc
        # tpu_operator_api_requests_total accounting: ON when this store IS
        # the operator's client; the REST façade (e2e/apiserver.py) turns it
        # OFF for its backing store so each logical request books exactly
        # once — at the ClusterClient that issued it, not again at the store
        # that served it
        self.count_api_requests = True
        self._lock = threading.RLock()
        # kind -> {namespace/name -> obj}
        self._store: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._handlers: Dict[str, List[EventHandler]] = {}
        self._rv = 0  # resourceVersion counter
        self.events: List[Dict[str, Any]] = []  # recorded k8s Events
        self._pod_logs: Dict[str, List[str]] = {}  # namespace/name -> lines

    # ------------------------------------------------------------------ util
    def _bump(self, obj: Dict[str, Any]) -> None:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)

    def _kind_store(self, kind: str) -> Dict[str, Dict[str, Any]]:
        return self._store.setdefault(kind, {})

    def subscribe(self, kind: str, handler: EventHandler) -> None:
        with self._lock:
            self._handlers.setdefault(kind, []).append(handler)

    def unsubscribe(self, kind: str, handler: EventHandler) -> None:
        with self._lock:
            try:
                self._handlers.get(kind, []).remove(handler)
            except ValueError:
                pass

    def _notify(self, kind: str, event_type: str, obj: Dict[str, Any]) -> None:
        # snapshot under the lock: a concurrent unsubscribe must not make
        # the iteration skip an unrelated handler (list.remove shifts
        # indices under a live for-loop)
        with self._lock:
            handlers = list(self._handlers.get(kind, []))
        for h in handlers:
            h(event_type, objects.fast_deepcopy(obj))

    def _observe(self, verb: str, kind: str) -> None:
        if self.count_api_requests:
            _observe_api_request(verb, kind)

    def _check_fence(self, kind: str, obj: Dict[str, Any]) -> None:
        """Reject writes whose fencing token (engine/sharding.py, stamped
        into the body's annotations by a sharded engine's status write) is
        older than the named Lease's current generation.  Enforced HERE —
        the authoritative store — so the REST façade and http apiserver
        inherit it: a zombie shard that wakes up after a failover cannot
        clobber the new owner's writes through any backend.  Writes
        without a token, or naming a Lease that does not exist, pass
        (fencing is only in force where a lock object says who owns)."""
        annotations = (obj.get("metadata") or {}).get("annotations") or {}
        if not annotations:
            return
        # lazy import: engine <-> k8s would cycle at module scope
        from tf_operator_tpu.engine.sharding import (
            FENCE_ANNOTATION,
            parse_fence_token,
        )

        token = annotations.get(FENCE_ANNOTATION)
        if not token:
            return
        parsed = parse_fence_token(token)
        if parsed is None:
            return
        ns, name, gen = parsed
        with self._lock:
            lease = self._kind_store("Lease").get(f"{ns}/{name}")
            if lease is None:
                return
            current = int((lease.get("spec") or {}).get("generation", 0) or 0)
        if gen < current:
            global _METRICS
            if _METRICS is None:
                from tf_operator_tpu.engine import metrics as _m
                _METRICS = _m
            _METRICS.FENCING_REJECTIONS.inc({"kind": kind})
            raise StaleFencingTokenError(
                f"{kind} {objects.key_of(obj)}: fencing token generation "
                f"{gen} is stale (lease {ns}/{name} is at generation "
                f"{current}); the writer lost slot ownership"
            )

    @staticmethod
    def _strip_fence(obj: Dict[str, Any]) -> None:
        """Drop the fencing-token annotation from an object about to be
        stored (see update(); lazy import — engine <-> k8s would cycle at
        module scope)."""
        annotations = (obj.get("metadata") or {}).get("annotations")
        if not annotations:
            return
        from tf_operator_tpu.engine.sharding import FENCE_ANNOTATION

        annotations.pop(FENCE_ANNOTATION, None)

    # ------------------------------------------------------------- generic
    def create(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        self._observe("create", kind)
        with self._lock:
            key = objects.key_of(obj)
            store = self._kind_store(kind)
            if key in store:
                raise ConflictError(f"{kind} {key} already exists")
            obj = objects.fast_deepcopy(obj)
            meta = obj.setdefault("metadata", {})
            meta.setdefault("uid", objects.new_uid())
            meta.setdefault("creationTimestamp", objects.now_iso())
            self._bump(obj)
            store[key] = obj
        self._notify(kind, "ADDED", obj)
        # GC also covers the create-after-owner-delete race: a dependent
        # born to a dead owner (reconcile in flight while the CR was
        # deleted) is reaped immediately, as the k8s garbage collector
        # would on its next observation
        owner_uid = next(
            (
                ref.get("uid")
                for ref in obj["metadata"].get("ownerReferences", []) or []
                if ref.get("controller")
            ),
            None,
        )
        if self.gc and owner_uid is not None and not self._uid_alive(owner_uid):
            try:
                self._delete_internal(
                    kind,
                    obj["metadata"].get("namespace", "default"),
                    obj["metadata"]["name"],
                )
            except NotFoundError:
                pass
        return objects.fast_deepcopy(obj)

    def _uid_alive(self, uid: str) -> bool:
        with self._lock:
            return any(
                o["metadata"].get("uid") == uid
                for store in self._store.values()
                for o in store.values()
            )

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        self._observe("get", kind)
        with self._lock:
            store = self._kind_store(kind)
            key = f"{objects.normalize_namespace(kind, namespace)}/{name}"
            if key not in store:
                raise NotFoundError(f"{kind} {key}")
            return objects.fast_deepcopy(store[key])

    def update(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        self._observe("update", kind)
        with self._lock:
            # fence INSIDE the write's critical section (the lock is
            # reentrant): checked-then-released would let a takeover's
            # generation bump land between the check and the write,
            # applying a stale-token write the fence already blessed
            self._check_fence(kind, obj)
            key = objects.key_of(obj)
            store = self._kind_store(kind)
            if key not in store:
                raise NotFoundError(f"{kind} {key}")
            # optimistic concurrency: a stale resourceVersion is a conflict
            # (real apiserver semantics; leader election's CAS depends on it)
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            stored_rv = store[key].get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and stored_rv is not None and sent_rv != stored_rv:
                raise ConflictError(
                    f"{kind} {key}: resourceVersion {sent_rv} != {stored_rv}"
                )
            obj = objects.fast_deepcopy(obj)
            # the fencing token is a per-REQUEST assertion, never persisted
            # state: a full-object write that stored it (warm-pool claims
            # ride update, not update_status) would make every later
            # read-modify-write of the object — a kubelet status write, a
            # controllerRef adoption — replay the claimer's old token and
            # get fenced after any failover bumped the generation
            self._strip_fence(obj)
            self._bump(obj)
            store[key] = obj
        self._notify(kind, "MODIFIED", obj)
        return objects.fast_deepcopy(obj)

    def update_status(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        """Status-subresource write: merges obj's .status onto the STORED
        object (spec untouched — apiserver /status semantics), with the same
        optimistic-concurrency check as update().  This is the verb the
        engine's status write-back uses so a sync needs no GET-before-update:
        the in-hand object's resourceVersion rides along and a stale one
        surfaces as ConflictError for the caller's conflict-retry.

        The fencing check runs BEFORE the optimistic-concurrency check: a
        zombie's stale-token write must be rejected as a fencing event
        (counted, terminal) even when its resourceVersion happens to be
        current."""
        self._observe("update_status", kind)
        with self._lock:
            # same-critical-section fencing as update(): see there
            self._check_fence(kind, obj)
            key = objects.key_of(obj)
            store = self._kind_store(kind)
            if key not in store:
                raise NotFoundError(f"{kind} {key}")
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            stored_rv = store[key].get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and stored_rv is not None and sent_rv != stored_rv:
                raise ConflictError(
                    f"{kind} {key}: resourceVersion {sent_rv} != {stored_rv}"
                )
            merged = objects.fast_deepcopy(store[key])
            merged["status"] = objects.fast_deepcopy(obj.get("status", {}))
            self._bump(merged)
            store[key] = merged
        self._notify(kind, "MODIFIED", merged)
        return objects.fast_deepcopy(merged)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._observe("delete", kind)
        self._delete_internal(kind, namespace, name)

    def _delete_internal(self, kind: str, namespace: str, name: str) -> None:
        """delete() minus the api_requests tick — the GC cascade's path: a
        server-side garbage collection is not a client request, and booking
        it would skew the per-verb tally between the fake backend and the
        REST façade (whose backing store never counts)."""
        with self._lock:
            store = self._kind_store(kind)
            key = f"{objects.normalize_namespace(kind, namespace)}/{name}"
            if key not in store:
                raise NotFoundError(f"{kind} {key}")
            obj = store.pop(key)
            # restamp the delete with a fresh rv (real apiserver semantics;
            # the REST façade already does this): _notify runs outside the
            # lock, so a DELETED carrying the last stored rv could tie with
            # the update that wrote it and cache consumers ordering events
            # by rv (SharedIndexInformer) could not tell which came last
            self._bump(obj)
        self._notify(kind, "DELETED", obj)
        self._collect_garbage(namespace, obj.get("metadata", {}).get("uid"))

    def _collect_garbage(self, namespace: str, owner_uid: Optional[str]) -> None:
        """Owner-based cascading deletion — the role the k8s garbage
        collector plays for the reference (pods/services carry a
        controller ownerReference; deleting the job CR reaps them).
        Without this, a job deleted mid-reconcile strands its pods."""
        if not owner_uid or not self.gc:
            return
        with self._lock:
            dependents = [
                (kind, o["metadata"].get("namespace", "default"),
                 o["metadata"]["name"])
                for kind, store in self._store.items()
                for o in store.values()
                if any(
                    ref.get("uid") == owner_uid
                    for ref in o["metadata"].get("ownerReferences", []) or []
                )
            ]
        for dep_kind, dep_ns, dep_name in dependents:
            try:
                self._delete_internal(dep_kind, dep_ns, dep_name)
            except NotFoundError:
                pass  # lost a race with another deleter — already gone

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        self._observe("list", kind)
        with self._lock:
            namespace = objects.normalize_namespace(kind, namespace)
            out = []
            for obj in self._kind_store(kind).values():
                if namespace is not None and objects.namespace_of(obj) != namespace:
                    continue
                if selector and not objects.selector_matches(
                    selector, objects.labels_of(obj)
                ):
                    continue
                out.append(objects.fast_deepcopy(obj))
            return out

    # ------------------------------------------------------------- typed sugar
    def create_pod(self, pod: Dict[str, Any]) -> Dict[str, Any]:
        return self.create("Pod", pod)

    def get_pod(self, namespace: str, name: str) -> Dict[str, Any]:
        return self.get("Pod", namespace, name)

    def update_pod(self, pod: Dict[str, Any]) -> Dict[str, Any]:
        return self.update("Pod", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.delete("Pod", namespace, name)

    def list_pods(self, namespace=None, selector=None) -> List[Dict[str, Any]]:
        return self.list("Pod", namespace, selector)

    def create_service(self, svc: Dict[str, Any]) -> Dict[str, Any]:
        return self.create("Service", svc)

    def delete_service(self, namespace: str, name: str) -> None:
        self.delete("Service", namespace, name)

    def list_services(self, namespace=None, selector=None) -> List[Dict[str, Any]]:
        return self.list("Service", namespace, selector)

    # ------------------------------------------------------------- nodes
    # Node inventory for the cluster scheduler (engine/scheduler.py): each
    # Node models one TPU slice — chip capacity from its slice shape,
    # accelerator generation for the heterogeneity-aware policy.  Stored
    # as ordinary cluster-scoped objects, so the REST façade serves them
    # at /api/v1/nodes with no special casing.
    def add_node(self, name: str, shape: str = "v5e-8",
                 generation: str = "v5e") -> Dict[str, Any]:
        from tf_operator_tpu.engine.scheduler import make_node  # lazy: cycle

        return self.create("Node", make_node(name, shape, generation))

    def list_nodes(self) -> List[Dict[str, Any]]:
        return self.list("Node")

    # ------------------------------------------------------------- pod logs
    def append_pod_log(self, namespace: str, name: str, line: str) -> None:
        """Container stdout capture (written by the kubelet simulator; read
        by JobClient.get_logs the way the reference reads the pod log API)."""
        with self._lock:
            self._pod_logs.setdefault(f"{namespace}/{name}", []).append(line)

    def read_pod_log(self, namespace: str, name: str) -> str:
        with self._lock:
            return "\n".join(self._pod_logs.get(f"{namespace}/{name}", []))

    def all_pod_logs(self, namespace: Optional[str] = None) -> Dict[str, str]:
        """Snapshot of every pod's log (incl. pods already reaped by
        CleanPodPolicy — logs outlive the pod object, like a real log
        store). Locked: kubelet threads may be appending concurrently."""
        with self._lock:
            return {
                key.partition("/")[2]: "\n".join(lines)
                for key, lines in self._pod_logs.items()
                if namespace is None or key.startswith(namespace + "/")
            }

    # ------------------------------------------------------------- events
    def record_event(
        self,
        obj: Dict[str, Any],
        event_type: str,
        reason: str,
        message: str,
    ) -> None:
        """k8s Event recorder analogue (reference uses record.EventRecorder
        for every lifecycle edge — SURVEY.md §5.5)."""
        self.events.append(
            {
                "type": event_type,
                "reason": reason,
                "message": message,
                "involvedObject": {
                    "kind": obj.get("kind", ""),
                    "name": objects.name_of(obj),
                    "namespace": objects.namespace_of(obj),
                },
                "timestamp": objects.now_iso(),
            }
        )

    def events_for(
        self,
        name: str,
        event_type: Optional[str] = None,
        namespace: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        return [
            e
            for e in self.events
            if e["involvedObject"]["name"] == name
            and (event_type is None or e["type"] == event_type)
            and (namespace is None
                 or e["involvedObject"].get("namespace") == namespace)
        ]
