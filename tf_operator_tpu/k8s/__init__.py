from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.fake import FakeCluster

__all__ = ["objects", "FakeCluster"]
