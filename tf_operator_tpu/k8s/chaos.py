"""Chaos harness — deterministic, seeded fault injection for the operator.

The operator's value proposition is surviving the failure modes that kill
distributed TPU training: preempted slices, flaky apiservers, dropped watch
streams.  Nothing can be trusted to survive what cannot be provoked, so this
module provokes all of it, on demand and reproducibly:

  - **API error storms**: scheduled windows during which cluster operations
    fail with 429 (carrying Retry-After), 5xx, 409 conflicts, or connection
    resets — exercising the retry/classification layer in k8s/client.py and
    the manager's transient-error requeue policy.
  - **Stale reads**: get/list return one-write-behind copies with outdated
    resourceVersions, so optimistic-concurrency conflicts happen exactly the
    way a lagging apiserver cache causes them.
  - **Watch outages**: subscriber events are silently dropped for a window,
    then a 410-style ``("ERROR", {...})`` delivery forces consumers
    (SharedIndexInformer.relist) to repair by list+diff — the same contract a
    real watch 410 Gone imposes.
  - **Pod-level chaos**: preemptions (SIGKILL/137), OOM kills, node drains,
    plus a minimal chaos kubelet that marks created pods Running, so whole
    job lifecycles run against the fake cluster with no real containers.

Everything fires from an explicit schedule keyed to a **simulated clock**
advanced by :meth:`FaultInjector.step` — no real sleeps anywhere — and the
injector's event log is a pure function of the seed and schedule: two runs of
the same scenario produce byte-identical logs (asserted by tests/test_chaos.py).

``FaultInjector`` presents the same surface it wraps (the FakeCluster /
ClusterClient interface), so it composes transparently: the manager, engine,
informers, and SDK run against it unmodified.
"""
from __future__ import annotations

import heapq
import threading
from dataclasses import dataclass, field
from random import Random
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.fake import ApiError, ConflictError, NotFoundError
from tf_operator_tpu.k8s.informer import (
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
)


class SimClock:
    """Injectable simulated time: callable like time.time, advanced
    explicitly.  Handed to the engine (JobEngine(clock=...)) and the
    injector so expectation TTLs, ActiveDeadlineSeconds, and crash-loop
    backoff all march to the same deterministic beat.  Starts at epoch 0
    so scenario schedules read as plain elapsed seconds."""

    def __init__(self, start: float = 0.0) -> None:
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


class DeterministicQueue(RateLimitingQueue):
    """RateLimitingQueue whose delays all collapse to immediate adds: pop
    order becomes a pure function of add order (no timer threads firing on
    real wall-clock), which seeded chaos runs need to replay identically.
    Failure counts still accrue so num_requeues-based cap logic behaves."""

    def __init__(self) -> None:
        super().__init__(ItemExponentialFailureRateLimiter(base_delay=0.0))

    def add_after(self, item: Any, delay: float) -> None:  # noqa: ARG002
        self.add(item)

    def add_rate_limited(self, item: Any) -> float:
        self._rate_limiter.when(item)  # count the failure
        self.add(item)
        return 0.0


@dataclass
class _Storm:
    start: float
    end: float
    fault: str  # "429" | "500" | "502" | "503" | "504" | "conflict" | "reset" | "stale"
    ops: Optional[frozenset] = None  # None = all of create/get/update/delete/list
    kinds: Optional[frozenset] = None  # None = every kind
    retry_after: Optional[float] = None  # attached to 429/503 errors


@dataclass(order=True)
class _Scheduled:
    at: float
    seq: int
    label: str = field(compare=False)
    fn: Callable[[], None] = field(compare=False)
    # log stream the firing line lands in: captured at schedule time, so a
    # kubelet hook scheduled from shard A's create thread logs into A's
    # stream no matter which thread fires it (see FaultInjector log docs)
    stream: str = field(compare=False, default="")


class FaultInjector:
    """Wraps a FakeCluster (or anything with the same client surface) and
    injects scheduled faults.  See module docstring for the fault classes.

    The public bookkeeping consumed by soak assertions:
      - ``log``: deterministic event log of every scheduled action fired
      - ``stats``: counters of injected faults / dropped watch events
      - ``retryable_kills`` / ``permanent_kills``: per (job_key, replica_type)
        pod kills, for matching against persisted restart counters
      - ``pod_creates``: per job_key count of pod creations that got through
        (the hot-loop churn measurement)
    """

    _OPS = frozenset({"create", "get", "update", "delete", "list"})

    def __init__(
        self,
        inner,
        seed: int = 0,
        clock: Optional[SimClock] = None,
        kubelet: bool = True,
        pod_start_delay: float = 1.0,
        nodes: int = 4,
        pull_latency=None,
        init_latency=None,
    ) -> None:
        self.inner = inner
        self.clock = clock or SimClock()
        self.seed = seed
        self.rng = Random(seed)
        self.kubelet = kubelet
        self.pod_start_delay = pod_start_delay
        # Image-pull / runtime-init latency the chaos kubelet charges every
        # created pod before marking it Running — the dominant real-world
        # cold-start term the simulated 8ms path hides.  Each spec is None
        # (disabled, byte-identical to the historical kubelet), a float
        # (constant seconds), or a (lo, hi) tuple sampled uniformly from a
        # SEEDED PER-SHARD stream: samples are drawn at SCHEDULE time on
        # the creating thread (whose set_shard tag names the stream), so
        # with N shard threads each stream's draw order is a pure function
        # of that shard's own create order — the byte-identical-log-per-
        # seed contract survives latency injection.
        self.pull_latency = pull_latency
        self.init_latency = init_latency
        self._latency_rngs: Dict[str, Random] = {}
        self.nodes = nodes
        # Event log, kept as PER-SHARD STREAMS merged on read.  With one
        # control-plane process (the historical shape) everything lands in
        # the default "" stream and `log` renders exactly the old append
        # order.  With N shard threads, each thread tags itself via
        # set_shard(); lines (and the firing lines of events it scheduled)
        # land in its own stream, and `log` merges streams by
        # (sim-time, shard-id, per-stream order) — a total order that does
        # not depend on how the OS interleaved the threads, so the
        # byte-identical-log-per-seed guarantee survives sharding.
        self._streams: Dict[str, List[Tuple[float, str]]] = {}
        self._tls = threading.local()
        self.stats: Dict[str, int] = {}
        self.retryable_kills: Dict[Tuple[str, str], int] = {}
        self.permanent_kills: Dict[Tuple[str, str], int] = {}
        self.pod_creates: Dict[str, int] = {}
        self._storms: List[_Storm] = []
        self._outages: List[Tuple[float, float, frozenset]] = []
        self._schedule: List[_Scheduled] = []
        self._seq = 0
        self._node_rr = 0
        # (kind, ns/name) -> the object version just superseded by an update
        # (strictly older resourceVersion than stored) — the stale-read pool
        self._prev: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # original handler -> gate-wrapped handler, per kind (unsubscribe
        # must unregister the same callable that was registered)
        self._subs: Dict[str, List[Tuple[Callable, Callable]]] = {}
        self._lock = threading.RLock()
        # cluster scheduler attach point (engine/scheduler.py): when set,
        # drain_node evicts gang-reserved pods THROUGH the scheduler (the
        # whole gang requeues as a unit) before the generic per-node
        # sweep; None keeps the historical drain byte-identical
        self.scheduler = None
        # job flight recorder attach point (engine/timeline.py): when
        # set, every injected kill is stamped into the owning job's
        # timeline — root cause IN the timeline, not beside it in the
        # seeded log.  Recording never writes to the log, so the
        # byte-identical-per-seed contract holds with or without it.
        self.recorder = None
        # serving-fleet attach point (models/fleetsim.FleetHarness):
        # when set, request-plane faults (replica freeze, kill-mid-
        # decode) fire INTO the harness off this injector's schedule —
        # the harness shares the injector's SimClock, so the injector
        # log and the router/harness log march to one beat.  None (the
        # default, and every operator chaos scenario) leaves all
        # historical behavior byte-identical.
        self.fleet = None
        # scrape-fault storm windows: (start, end, mode, replicas|None)
        self._scrape_storms: List[Tuple[float, float, str, Optional[frozenset]]] = []
        if kubelet:
            self.inner.subscribe("Pod", self._kubelet_on_pod)

    # ----------------------------------------------------------- bookkeeping
    def _count(self, what: str, n: int = 1) -> None:
        with self._lock:
            self.stats[what] = self.stats.get(what, 0) + n

    def set_shard(self, shard: Optional[str]) -> None:
        """Tag the calling thread as shard `shard`: its subsequent log
        lines (and events it schedules) land in that shard's stream.
        None restores the default stream."""
        self._tls.shard = shard

    def _current_stream(self) -> str:
        return getattr(self._tls, "shard", None) or ""

    def _log(
        self, line: str, t: Optional[float] = None, stream: Optional[str] = None
    ) -> None:
        with self._lock:
            sid = self._current_stream() if stream is None else stream
            entries = self._streams.setdefault(sid, [])
            ts = self.clock() if t is None else t
            if entries and entries[-1][0] > ts:
                # monotone clamp per stream: a direct log at clock() can
                # follow a scheduled line whose `at` was earlier — the
                # merge sort must never reorder a stream's append order
                ts = entries[-1][0]
            entries.append((ts, line))

    @property
    def log(self) -> List[str]:
        """The merged deterministic event log: streams interleaved by
        (sim-time, shard-id, within-stream order).  Single-stream runs
        render their exact append order (the pre-shard byte-identity
        contract, asserted against the golden file)."""
        with self._lock:
            merged = [
                (ts, sid, idx, line)
                for sid, entries in self._streams.items()
                for idx, (ts, line) in enumerate(entries)
            ]
        merged.sort(key=lambda e: (e[0], e[1], e[2]))
        return [line for _, _, _, line in merged]

    def note(self, label: str) -> None:
        """Record an external actor's event (shard failover, lease
        takeover, re-adopt sweep) at the current simulated time, in the
        calling thread's stream — the hook the sharded control plane uses
        so its decisions appear in the deterministic log."""
        self._log(f"t={self.clock():g} {label}")

    @staticmethod
    def _job_of(pod: Dict[str, Any]) -> Optional[Tuple[str, str]]:
        labels = objects.labels_of(pod)
        job = labels.get(objects.LABEL_JOB_NAME)
        rtype = labels.get(objects.LABEL_REPLICA_TYPE)
        if not job or not rtype:
            return None
        return f"{objects.namespace_of(pod)}/{job}", rtype

    # ------------------------------------------------------------- schedule
    def at(self, t: float, fn: Callable[[], None], label: str) -> None:
        """Schedule `fn` at simulated time `t` (absolute); fired by step().
        Locked: with control fan-out > 1 the chaos kubelet's hooks fire
        from concurrent create threads, and an unlocked seq++/heappush
        pair would corrupt the schedule heap."""
        with self._lock:
            self._seq += 1
            heapq.heappush(
                self._schedule,
                _Scheduled(t, self._seq, label, fn, self._current_stream()),
            )

    def after(self, dt: float, fn: Callable[[], None], label: str) -> None:
        self.at(self.clock() + dt, fn, label)

    def step(self, dt: float = 1.0) -> None:
        """Advance the simulated clock and fire everything that came due, in
        (time, schedule-order) order — the single source of chaos, so the
        event log replays identically for a given seed + schedule.  The
        pop+log pair holds the schedule lock; the action itself runs
        outside it (actions create/update objects, which may schedule
        follow-ups through at() — RLock-safe, but holding the lock across
        store calls would serialize against every concurrent fan-out op)."""
        self.clock.advance(dt)
        now = self.clock()
        while True:
            with self._lock:
                if not self._schedule or self._schedule[0].at > now:
                    return
                item = heapq.heappop(self._schedule)
                self._log(f"t={item.at:g} {item.label}", t=item.at,
                          stream=item.stream)
            item.fn()

    def run_until(self, t: float, dt: float = 1.0) -> None:
        while self.clock() < t:
            self.step(dt)

    # ------------------------------------------------------------- storms
    def schedule_storm(
        self,
        start: float,
        duration: float,
        fault: str = "500",
        ops: Optional[List[str]] = None,
        kinds: Optional[List[str]] = None,
        retry_after: Optional[float] = None,
    ) -> None:
        """All matching API calls in [start, start+duration) fail with
        `fault` (429/5xx/conflict/reset) or return stale data (fault="stale").
        Times are absolute simulated seconds."""
        storm = _Storm(
            start=start,
            end=start + duration,
            fault=fault,
            ops=frozenset(ops) if ops else None,
            kinds=frozenset(kinds) if kinds else None,
            retry_after=retry_after,
        )
        self._storms.append(storm)
        scope = ",".join(sorted(storm.ops)) if storm.ops else "*"
        self.at(start, lambda: None, f"storm_begin fault={fault} ops={scope}")
        self.at(storm.end, lambda: None, f"storm_end fault={fault}")

    def schedule_watch_outage(
        self, start: float, duration: float, kinds: Tuple[str, ...] = ("Pod", "Service")
    ) -> None:
        """Watch events for `kinds` are silently dropped in [start,
        start+duration); at the end every subscriber receives a 410-style
        ERROR so it can repair by relist (informers) or ignore it (handlers
        that only react to ADDED/DELETED, like expectation observers — their
        losses are healed by expectation TTL expiry)."""
        window = (start, start + duration, frozenset(kinds))
        self._outages.append(window)
        self.at(start, lambda: None, f"watch_outage_begin kinds={','.join(sorted(kinds))}")
        self.at(
            start + duration,
            lambda: self._end_watch_outage(kinds),
            f"watch_outage_end kinds={','.join(sorted(kinds))}",
        )

    def _watch_blocked(self, kind: str) -> bool:
        now = self.clock()
        return any(s <= now < e and kind in ks for (s, e, ks) in self._outages)

    def _end_watch_outage(self, kinds: Tuple[str, ...]) -> None:
        err = {"code": 410, "reason": "chaos watch outage"}
        with self._lock:
            targets = [
                wrapped
                for kind in kinds
                for (_h, wrapped) in self._subs.get(kind, [])
            ]
        for wrapped in targets:
            wrapped("ERROR", dict(err))

    def _fault(self, op: str, kind: str) -> Optional[str]:
        """Raise the active storm's error for this op, or return "stale" for
        a stale-read window, or None when the path is clear."""
        now = self.clock()
        for s in self._storms:
            if not (s.start <= now < s.end):
                continue
            if s.ops is not None and op not in s.ops:
                continue
            if s.kinds is not None and kind not in s.kinds:
                continue
            self._count(f"fault.{s.fault}")
            if s.fault == "stale":
                return "stale"
            if s.fault == "conflict":
                raise ConflictError(f"chaos: injected conflict on {op} {kind}")
            if s.fault == "reset":
                raise ConnectionResetError(f"chaos: connection reset on {op} {kind}")
            raise ApiError(
                int(s.fault),
                f"chaos: injected {s.fault} on {op} {kind}",
                retry_after=s.retry_after,
            )
        return None

    # --------------------------------------------------------- pod chaos
    def _latency_rng(self, stream: str) -> Random:
        """Seeded per-shard latency stream.  Random(str) seeds via a
        stable digest of the string (not the per-process-salted hash()),
        so the same (seed, shard) pair draws the same sequence in every
        process and run."""
        rng = self._latency_rngs.get(stream)
        if rng is None:
            rng = Random(f"{self.seed}:kubelet-latency:{stream}")
            self._latency_rngs[stream] = rng
        return rng

    @staticmethod
    def _sample_latency(spec, rng: Random) -> float:
        if not spec:
            return 0.0
        if isinstance(spec, (int, float)):
            return float(spec)
        lo, hi = spec
        return rng.uniform(lo, hi)

    def _kubelet_on_pod(self, event_type: str, pod: Dict[str, Any]) -> None:
        if event_type != "ADDED":
            return
        ns, name = objects.namespace_of(pod), objects.name_of(pod)
        delay = self.pod_start_delay
        label = f"kubelet_start pod={ns}/{name}"
        if self.pull_latency or self.init_latency:
            # schedule-time capture from the creating thread's stream:
            # the draw order within a stream is the shard's own create
            # order, immune to how the OS interleaves other shards
            with self._lock:
                rng = self._latency_rng(self._current_stream())
                pull = self._sample_latency(self.pull_latency, rng)
                init = self._sample_latency(self.init_latency, rng)
            delay += pull + init
            label += f" pull={pull:g} init={init:g}"
        created_at = self.clock()
        self.after(
            delay,
            lambda: self._mark_running(ns, name, created_at=created_at),
            label,
        )

    def _mark_running(
        self, namespace: str, name: str, created_at: Optional[float] = None
    ) -> None:
        try:
            pod = self.inner.get_pod(namespace, name)
        except (NotFoundError, ApiError):
            return
        if objects.pod_phase(pod) not in ("", None, "Pending"):
            return  # already progressed (or chaos got there first)
        containers = pod.get("spec", {}).get("containers", []) or [{}]
        cname = containers[0].get("name", "main")
        self._node_rr += 1
        pod.setdefault("status", {})
        pod["status"]["phase"] = objects.POD_RUNNING
        pod["status"]["containerStatuses"] = [
            {"name": cname, "state": {"running": {}}, "restartCount": 0}
        ]
        # a pod the scheduler already bound (spec.nodeName stamped at
        # create) keeps its placement — the kubelet only picks a node for
        # unscheduled pods, so the historical round-robin (and the seeded
        # chaos goldens, whose pods are never pre-bound) is unchanged
        if not pod["spec"].get("nodeName"):
            # honor cordons: an unscheduled pod (warm-pool standby
            # replenishment, mostly) must not land on a node mid-drain.
            # With no scheduler attached or nothing cordoned the pick is
            # the historical round-robin, byte-identical
            cordoned = (
                self.scheduler.cordoned_nodes()
                if self.scheduler is not None else frozenset()
            )
            cand = f"chaos-node-{self._node_rr % self.nodes}"
            for _ in range(self.nodes):
                if cand not in cordoned:
                    break
                self._node_rr += 1
                cand = f"chaos-node-{self._node_rr % self.nodes}"
            pod["spec"]["nodeName"] = cand
        try:
            self.inner.update_pod(pod)
        except (ConflictError, NotFoundError, ApiError):
            return  # lost a race with a concurrent writer; next event retries
        if created_at is not None:
            # cold-vs-warm cold-start evidence: a pool standby pays the
            # pull/init latency as pool_fill (off any job's critical
            # path); every other pod is a job replica's cold start.  Lazy
            # import: engine/__init__ pulls the controller, which imports
            # k8s modules — same cycle fake.py dodges.
            from tf_operator_tpu.engine import metrics as _metrics
            from tf_operator_tpu.engine import warmpool as _warmpool

            path = "pool_fill" if _warmpool.is_warm_pool_pod(pod) else "cold"
            _metrics.CREATE_TO_RUNNING.observe(
                max(0.0, self.clock() - created_at), {"path": path}
            )

    def kill_pod(
        self, namespace: str, name: str, exit_code: int = 137,
        reason: str = "Preempted",
    ) -> bool:
        """Terminate a running pod with `exit_code` (137 = SIGKILL class:
        preemption/OOM; 1-127 = permanent user error).  Books the kill
        against the owning job's replica type for the restart-counter
        invariant.  Returns False when the pod is not currently Running."""
        try:
            pod = self.inner.get_pod(namespace, name)
        except (NotFoundError, ApiError):
            self._count("kill.miss")
            return False
        if objects.pod_phase(pod) != objects.POD_RUNNING:
            self._count("kill.miss")
            return False
        containers = pod.get("spec", {}).get("containers", []) or [{}]
        cname = containers[0].get("name", "main")
        pod["status"]["phase"] = objects.POD_FAILED
        pod["status"]["reason"] = reason
        pod["status"]["containerStatuses"] = [{
            "name": cname,
            "state": {"terminated": {"exitCode": exit_code, "reason": reason}},
            "restartCount": 0,
        }]
        try:
            self.inner.update_pod(pod)
        except (ConflictError, NotFoundError):
            self._count("kill.miss")
            return False
        owner = self._job_of(pod)
        if owner is not None:
            book = (
                self.retryable_kills if exit_code >= 128 else self.permanent_kills
            )
            with self._lock:
                book[owner] = book.get(owner, 0) + 1
            if self.recorder is not None:
                self.recorder.record(
                    owner[0], "chaos", "kill",
                    {"pod": f"{namespace}/{name}", "exit_code": exit_code,
                     "reason": reason, "replica_type": owner[1]},
                    ts=self.clock(),
                )
        self._count("kill.hit")
        self._log(
            f"t={self.clock():g} kill pod={namespace}/{name} "
            f"code={exit_code} reason={reason}"
        )
        return True

    def running_pods(self) -> List[Dict[str, Any]]:
        return sorted(
            (
                p
                for p in self.inner.list_pods()
                if objects.pod_phase(p) == objects.POD_RUNNING
            ),
            key=objects.key_of,
        )

    def kill_random_running_pod(
        self, exit_code: int = 137, reason: str = "Preempted"
    ) -> Optional[str]:
        """Kill one seeded-random Running pod (sorted candidate list keeps
        the choice a function of cluster state + seed, not dict order)."""
        pods = self.running_pods()
        if not pods:
            self._count("kill.miss")
            return None
        pod = pods[self.rng.randrange(len(pods))]
        ns, name = objects.namespace_of(pod), objects.name_of(pod)
        self.kill_pod(ns, name, exit_code=exit_code, reason=reason)
        return f"{ns}/{name}"

    def drain_node(self, node: str) -> int:
        """Node drain: every Running pod bound to `node` dies with 137
        (preemption-class), like a TPU host reclaim.  With a scheduler
        attached, gangs holding a reservation on the node are evicted
        FIRST and as a unit — a TPU slice is unusable partially, so the
        gang's members on other nodes die too, its reservation is
        released, and the job re-enters gang admission wholesale; the
        generic sweep then catches anything unscheduled (warm standbys,
        legacy pods).  Each kill routes through kill_pod, so the seeded
        event log carries the node name and every killed pod either way."""
        n = 0
        if self.scheduler is not None:
            n += self.scheduler.drain_node(
                node,
                kill=lambda ns, name: self.kill_pod(
                    ns, name, exit_code=137, reason="NodeDrain"
                ),
            )
        for pod in self.running_pods():
            if pod.get("spec", {}).get("nodeName") == node:
                if self.kill_pod(
                    objects.namespace_of(pod), objects.name_of(pod),
                    exit_code=137, reason="NodeDrain",
                ):
                    n += 1
        self._log(f"t={self.clock():g} drain node={node} killed={n}")
        return n

    # ------------------------------------------------- serving faults
    # Chaos at the request plane (ISSUE 15): seeded, sim-clock-scheduled
    # faults against a serving FLEET — the harness (models/fleetsim.py)
    # consults scrape_fault() at every heartbeat and registers itself as
    # `fleet` so freeze/kill events fire into it.  Everything lands in
    # this injector's deterministic log; nothing here touches the
    # cluster surface, so the operator chaos goldens are unaffected.

    def schedule_scrape_storm(
        self,
        start: float,
        duration: float,
        mode: str = "timeout",
        replicas: Optional[List[str]] = None,
    ) -> None:
        """Scrapes of `replicas` (None = every replica) fail with `mode`
        (timeout / 500 / truncated) in [start, start+duration) — the
        monitoring-plane outage the router's ejection ladder and
        degraded fallback exist for."""
        window = (
            start, start + duration, mode,
            # [] is an explicit empty scope (a dynamically-built list
            # that matched nothing), NOT "every replica" — only None
            # means fleet-wide
            frozenset(replicas) if replicas is not None else None,
        )
        self._scrape_storms.append(window)
        scope = (
            ",".join(sorted(replicas)) if replicas is not None else "*"
        )
        self.at(
            start, lambda: None,
            f"scrape_storm_begin mode={mode} replicas={scope}",
        )
        self.at(
            start + duration, lambda: None,
            f"scrape_storm_end mode={mode}",
        )

    def scrape_fault(self, replica: str) -> Optional[str]:
        """The active scrape-storm mode covering `replica` right now, or
        None when the scrape path is clear.  Counted per consultation."""
        now = self.clock()
        for start, end, mode, scope in self._scrape_storms:
            if start <= now < end and (scope is None or replica in scope):
                self._count(f"scrape.{mode}")
                return mode
        return None

    def schedule_replica_freeze(self, at: float, replica: str) -> None:
        """Freeze a serving replica at simulated time `at`: it keeps
        accepting dispatches and (unless a scrape storm also covers it)
        keeps heartbeating healthy telemetry, but never makes progress —
        the SIGSTOP'd decode thread whose metrics thread lives.  Only
        hedged re-dispatch rescues its in-flight requests."""
        self.at(
            at,
            lambda: self.fleet is not None and self.fleet.freeze(replica),
            f"freeze replica={replica}",
        )

    def schedule_replica_kill(self, at: float, replica: str) -> None:
        """Kill a serving replica mid-decode at simulated time `at`: it
        stops heartbeating AND computing — health expiry re-dispatches
        its orphans exactly once."""
        self.at(
            at,
            lambda: self.fleet is not None and self.fleet.kill_now(replica),
            f"kill_mid_decode replica={replica}",
        )

    # ------------------------------------------------- intercepted surface
    def create(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        self._fault("create", kind)
        out = self.inner.create(kind, obj)
        if kind == "Pod":
            owner = self._job_of(out)
            if owner is not None:
                with self._lock:
                    self.pod_creates[owner[0]] = (
                        self.pod_creates.get(owner[0], 0) + 1
                    )
        return out

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        mode = self._fault("get", kind)
        out = self.inner.get(kind, namespace, name)
        if mode == "stale":
            prev = self._prev.get((kind, f"{namespace}/{name}"))
            if prev is not None:
                self._count("stale.get")
                return objects.fast_deepcopy(prev)
        return out

    def update(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        self._fault("update", kind)
        key = objects.key_of(obj)
        try:
            superseded = self.inner.get(
                kind, objects.namespace_of(obj), objects.name_of(obj)
            )
        except (NotFoundError, ApiError):
            superseded = None
        out = self.inner.update(kind, obj)
        if superseded is not None:
            self._prev[(kind, key)] = superseded
        return out

    def update_status(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        """Status-subresource writes are update-class faults: the engine's
        hot-path status write-back moved to this verb, and letting it slip
        past the injector via __getattr__ would exempt the single most
        frequent write from conflict/5xx storms (ops=["update"] covers
        both verbs)."""
        self._fault("update", kind)
        key = objects.key_of(obj)
        try:
            superseded = self.inner.get(
                kind, objects.namespace_of(obj), objects.name_of(obj)
            )
        except (NotFoundError, ApiError):
            superseded = None
        out = self.inner.update_status(kind, obj)
        if superseded is not None:
            self._prev[(kind, key)] = superseded
        return out

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._fault("delete", kind)
        self.inner.delete(kind, namespace, name)
        self._prev.pop((kind, f"{namespace}/{name}"), None)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        mode = self._fault("list", kind)
        items = self.inner.list(kind, namespace, selector)
        if mode == "stale":
            out = []
            for item in items:
                prev = self._prev.get((kind, objects.key_of(item)))
                if prev is not None:
                    self._count("stale.list")
                    out.append(objects.fast_deepcopy(prev))
                else:
                    out.append(item)
            return out
        return items

    # typed sugar routes through the generic ops so faults apply uniformly
    def create_pod(self, pod):
        return self.create("Pod", pod)

    def get_pod(self, namespace, name):
        return self.get("Pod", namespace, name)

    def update_pod(self, pod):
        return self.update("Pod", pod)

    def delete_pod(self, namespace, name):
        self.delete("Pod", namespace, name)

    def list_pods(self, namespace=None, selector=None):
        return self.list("Pod", namespace, selector)

    def create_service(self, svc):
        return self.create("Service", svc)

    def delete_service(self, namespace, name):
        self.delete("Service", namespace, name)

    def list_services(self, namespace=None, selector=None):
        return self.list("Service", namespace, selector)

    # ------------------------------------------------------------- watches
    def subscribe(self, kind: str, handler: Callable) -> None:
        def gated(event_type: str, obj: Dict[str, Any]) -> None:
            if event_type != "ERROR" and self._watch_blocked(kind):
                self._count(f"watch.dropped.{kind}")
                return
            handler(event_type, obj)

        with self._lock:
            self._subs.setdefault(kind, []).append((handler, gated))
        self.inner.subscribe(kind, gated)

    def unsubscribe(self, kind: str, handler: Callable) -> None:
        with self._lock:
            pairs = self._subs.get(kind, [])
            gated = next((w for (h, w) in pairs if h is handler), None)
            if gated is not None:
                pairs.remove((handler, gated))
        if gated is not None:
            self.inner.unsubscribe(kind, gated)

    # ------------------------------------------------------------ passthrough
    def __getattr__(self, name: str):
        # everything not intercepted (record_event, events_for, pod logs,
        # gc flag, ...) is the inner cluster's business
        return getattr(self.inner, name)
