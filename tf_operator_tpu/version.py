"""Version info printed at operator startup (reference
pkg/version/version.go:21-24: Version + GitSHA)."""
from __future__ import annotations

import subprocess

VERSION = "0.1.0"
_git_sha_cache: str | None = None


def git_sha() -> str:
    global _git_sha_cache
    if _git_sha_cache is None:
        try:
            _git_sha_cache = (
                subprocess.run(
                    ["git", "rev-parse", "--short", "HEAD"],
                    capture_output=True,
                    text=True,
                    timeout=5,
                ).stdout.strip()
                or "unknown"
            )
        except Exception:
            _git_sha_cache = "unknown"
    return _git_sha_cache


def version_string() -> str:
    return f"tpu-operator {VERSION} (git {git_sha()})"
