"""Version info printed at operator startup (reference
pkg/version/version.go:21-24: Version + GitSHA)."""
from __future__ import annotations

import os
import subprocess

VERSION = "0.1.0"
_git_sha_cache: str | None = None


def git_sha() -> str:
    global _git_sha_cache
    if _git_sha_cache is None:
        try:
            # resolve against the PACKAGE's checkout, not the caller's CWD
            # — an installed `tpu-jobs version` run inside some unrelated
            # repo must not present that repo's HEAD as the operator build
            _git_sha_cache = (
                subprocess.run(
                    ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
                     "rev-parse", "--short", "HEAD"],
                    capture_output=True,
                    text=True,
                    timeout=5,
                ).stdout.strip()
                or "unknown"
            )
        except Exception:
            _git_sha_cache = "unknown"
    return _git_sha_cache


def version_string() -> str:
    return f"tpu-operator {VERSION} (git {git_sha()})"
