"""Training loop runner — checkpoint/resume + preemption-aware save + profiler.

The reference's "resume" is pod recreation with stable identity; the
framework inside the container is responsible for restoring its own state
(SURVEY.md §5.4). This module is that framework side, TPU-first:

  - resume-from-latest on start (the recreated pod finds its checkpoint);
  - periodic async-friendly saves every `save_interval_steps`;
  - preemption-aware save: SIGTERM (TPU maintenance/preemption sends it
    ahead of the kill) triggers one final checkpoint, so a whole-slice
    gang restart (controllers/tpu.py exit-code policy) loses at most the
    in-flight step, not the save interval;
  - profiler hooks (runtime/profiler.py) + metrics lines on stdout.

The loop itself stays jit-friendly: the python loop only feeds batches and
reads back metrics; the step is one compiled SPMD program.
"""
from __future__ import annotations

import signal
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Optional

from tf_operator_tpu.runtime.profiler import Profiler
from tf_operator_tpu.runtime.train import Checkpointer, TrainState
from tf_operator_tpu.utils.logging import get_logger

log = get_logger("runtime.loop")


class PreemptionGuard:
    """Latches SIGTERM/SIGINT so the loop can checkpoint before dying.

    TPU preemption/maintenance deletes the pod; kubelet delivers SIGTERM
    and waits terminationGracePeriodSeconds — enough for one save. The
    guard only latches a flag; the loop decides when to act (never save
    mid-step)."""

    def __init__(self, install: bool = True) -> None:
        self._preempted = threading.Event()
        self._prev_handlers: Dict[int, Any] = {}
        if install and threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)

    def _on_signal(self, signum, frame) -> None:
        log.warning("received signal %s: will checkpoint and stop", signum)
        self._preempted.set()

    def trigger(self) -> None:
        """Test hook / manual preemption injection."""
        self._preempted.set()

    @property
    def preempted(self) -> bool:
        return self._preempted.is_set()

    def uninstall(self) -> None:
        for sig, handler in self._prev_handlers.items():
            signal.signal(sig, handler)
        self._prev_handlers.clear()


@dataclass
class LoopResult:
    state: Any
    steps_run: int
    preempted: bool
    resumed_from: Optional[int]
    last_metrics: Dict[str, float]
    # goodput/MFU split for the session (GoodputTracker.summary()):
    # productive/checkpoint/replay/idle fractions + goodput, mfu when the
    # profiler was given flops_per_step/peak_flops_per_sec
    goodput: Dict[str, float] = field(default_factory=dict)
    # the step the newest durable checkpoint holds on exit (None when no
    # checkpointer / nothing saved).  The elastic-resize drain contract
    # reads this: a SIGTERMed loop's final save must equal the step it
    # actually reached, so the resharded resume loses at most the
    # in-flight step — asserted by the resize soak/loss tests.
    last_saved_step: Optional[int] = None


def run_training(
    state: TrainState,
    train_step: Callable,
    batches: Iterable,
    num_steps: int,
    checkpointer: Optional[Checkpointer] = None,
    save_interval_steps: int = 100,
    profiler: Optional[Profiler] = None,
    guard: Optional[PreemptionGuard] = None,
    log_interval_steps: int = 50,
    metrics_sink: Optional[Callable[[str], None]] = None,
) -> LoopResult:
    """Run up to `num_steps` total steps (counting restored progress).

    `batches` yields (inputs, labels) tuples; `train_step(state, *batch)`
    returns (state, metrics). Resume: if `checkpointer` has a saved step,
    restore and continue from there — the recreated pod converges to the
    same loop position (reference semantics: identical pod name/DNS, state
    from the framework's own checkpoint)."""
    profiler = profiler or Profiler()
    profiler.goodput.start()  # wall clock runs from here; restore is replay
    resumed_from = None
    if checkpointer is not None:
        latest = checkpointer.latest_step()
        if latest is not None:
            with profiler.goodput.resume_replay():
                state = checkpointer.restore(state)
            resumed_from = latest
            log.info("resumed from checkpoint step %d", latest)

    guard = guard or PreemptionGuard(install=False)
    step = int(state.step)
    steps_run = 0
    last_saved_step = resumed_from if resumed_from is not None else -1
    last_metrics: Dict[str, float] = {}
    it = iter(batches)

    try:
        try:
            while step < num_steps:
                if guard.preempted:
                    break
                profiler.maybe_trace(step)
                try:
                    batch = next(it)
                except StopIteration:
                    break
                with profiler.step(step):
                    state, metrics = train_step(state, *batch)
                step += 1
                steps_run += 1
                last_metrics = {k: float(v) for k, v in metrics.items()}

                if checkpointer is not None and step % save_interval_steps == 0:
                    with profiler.goodput.checkpoint_save():
                        checkpointer.save(step, state)
                    last_saved_step = step
                if step % log_interval_steps == 0:
                    line = profiler.metrics_line(step, extra=last_metrics)
                    (metrics_sink or (lambda s: log.info("%s", s)))(line)
        finally:
            # flush an unfinished trace window even when a step raises mid-
            # window: the jax profiler is process-global, and leaving it
            # started loses the capture AND breaks any later start_trace()
            profiler.stop_trace()
        preempted = guard.preempted
        if checkpointer is not None and steps_run > 0 and step != last_saved_step:
            # final save unless this exact step is already on disk (interval
            # save this iteration, or a recreated pod that restored an
            # already-complete run) — orbax raises on duplicate steps.
            # wait=True: the exit/preemption save must be durable before the
            # process dies, even in async mode
            with profiler.goodput.checkpoint_save():
                checkpointer.save(step, state, wait=True)
            last_saved_step = step
        elif checkpointer is not None:
            # async interval saves may still be in flight; drain before return
            with profiler.goodput.checkpoint_save():
                checkpointer.wait_until_finished()
    finally:
        # the goodput wall clock must freeze on every exit path — a caller
        # reading summary() after a crashed step, or retrying with the same
        # profiler, must not have the downtime charged as idle
        profiler.goodput.stop()
    return LoopResult(
        state=state,
        steps_run=steps_run,
        preempted=preempted,
        resumed_from=resumed_from,
        last_metrics=last_metrics,
        goodput=profiler.goodput.summary(),
        last_saved_step=(
            last_saved_step if checkpointer is not None
            and last_saved_step >= 0 else None
        ),
    )
