"""Training runtime — sharded train/eval steps + checkpointing.

The compute path the reference leaves to in-container TF (SURVEY.md §3.4
'in-pod training bootstrap'), built TPU-first: one jitted SPMD train step
over a `jax.sharding.Mesh`; params replicated across dp and sharded over
fsdp; batches sharded over (dp, fsdp); XLA inserts the gradient psum over
ICI. Checkpoint/resume uses orbax (the operator recreates pods with stable
identity so the runtime can restore — SURVEY.md §5.4).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import core as flax_core
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tf_operator_tpu.parallel.mesh import DEFAULT_RULES, MeshRules


class TrainState(struct.PyTreeNode):
    """Minimal train state: params + opt state + optional batch stats."""

    step: jnp.ndarray
    params: Any
    opt_state: Any
    batch_stats: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads, new_batch_stats=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
        )


def create_train_state(
    rng: jax.Array,
    model,
    sample_input: jax.Array,
    tx: optax.GradientTransformation,
) -> TrainState:
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", flax_core.FrozenDict())
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        batch_stats=batch_stats,
        tx=tx,
    )


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    # integer-label CE: no [B, ..., vocab] one-hot temporary in the hot path
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def fsdp_param_sharding(params, mesh: Mesh, min_size: int = 2**14):
    """Shard each large param along its largest fsdp-divisible dim; small
    params replicate. The standard fsdp placement — params live sharded in
    HBM, XLA all-gathers just-in-time per layer."""
    from tf_operator_tpu.parallel.mesh import pick_fsdp_dim

    fsdp = mesh.shape.get("fsdp", 1)

    def place(x):
        shape = getattr(x, "shape", ())
        d = pick_fsdp_dim(shape, fsdp, min_size)
        if d is not None:
            spec = [None] * len(shape)
            spec[d] = "fsdp"
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(place, params)


def make_train_step(
    model,
    loss_fn: Callable = cross_entropy_loss,
    has_batch_stats: bool = True,
    rules: MeshRules = DEFAULT_RULES,
    mesh: Optional[Mesh] = None,
    accum_steps: int = 1,
    state_shardings: Optional[Any] = None,
):
    """Build the jitted SPMD train step: (state, images, labels) ->
    (state, metrics). Everything inside is traced once; no python branching
    on data.

    `state_shardings` (a TrainState of NamedShardings, e.g. from
    parallel/tp.state_sharding) pins the OUTPUT state sharding. Without it
    XLA's propagation is free to emit the updated params under a different
    sharding than the input state (observed: tp moved / fsdp added on a
    multi-axis mesh), which silently reshards every step — and, if the
    caller jits a wrapper with explicit `in_shardings`, fails the second
    step outright because the donated output no longer matches. Requires
    `mesh` (metrics scalars are pinned replicated on it).

    `accum_steps > 1` enables gradient accumulation: the batch is split
    into that many micro-batches, a `lax.scan` runs fwd+bwd per micro-batch
    summing gradients, and ONE optimizer update applies the mean — the
    standard HBM <-> batch-size trade (activation memory scales with the
    micro-batch, not the global batch). Equal-sized micro-batches make the
    mean-of-means equal the full-batch mean loss/grad, so for BN-free
    models the update is numerically the full-batch update."""

    def forward_backward(params, batch_stats, x, y):
        def compute_loss(p):
            variables = {"params": p}
            if has_batch_stats:
                variables["batch_stats"] = batch_stats
                logits, updates = model.apply(
                    variables, x, train=True, mutable=["batch_stats"]
                )
                return loss_fn(logits, y), (logits, updates["batch_stats"])
            logits = model.apply(variables, x, train=True)
            return loss_fn(logits, y), (logits, None)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(params)
        accuracy = jnp.mean(jnp.argmax(logits, -1) == y)
        return loss, accuracy, new_stats, grads

    def step(state: TrainState, images: jax.Array, labels: jax.Array):
        if mesh is not None:
            batch_spec = P(rules.mesh_axes("batch"))
            images = jax.lax.with_sharding_constraint(
                images, NamedSharding(mesh, batch_spec)
            )

        if accum_steps == 1:
            loss, accuracy, new_stats, grads = forward_backward(
                state.params, state.batch_stats, images, labels
            )
            new_state = state.apply_gradients(grads, new_batch_stats=new_stats)
            return new_state, {"loss": loss, "accuracy": accuracy}

        b = images.shape[0]
        if b % accum_steps != 0:
            raise ValueError(
                f"batch size {b} not divisible by accum_steps {accum_steps}"
            )
        micro = b // accum_steps
        mi = images.reshape(accum_steps, micro, *images.shape[1:])
        ml = labels.reshape(accum_steps, micro, *labels.shape[1:])

        def body(carry, xs):
            grads_acc, loss_acc, acc_acc, bs = carry
            x, y = xs
            loss, accuracy, new_stats, grads = forward_backward(
                state.params, bs, x, y
            )
            carry = (
                jax.tree.map(jnp.add, grads_acc, grads),
                loss_acc + loss,
                acc_acc + accuracy,
                new_stats if has_batch_stats else bs,
            )
            return carry, None

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        (grads_sum, loss_sum, acc_sum, new_stats), _ = jax.lax.scan(
            body,
            (zeros, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
             state.batch_stats),
            (mi, ml),
        )
        grads = jax.tree.map(lambda g: g / accum_steps, grads_sum)
        new_state = state.apply_gradients(
            grads, new_batch_stats=new_stats if has_batch_stats else None
        )
        return new_state, {
            "loss": loss_sum / accum_steps,
            "accuracy": acc_sum / accum_steps,
        }

    kw = {}
    if state_shardings is not None:
        if mesh is None:
            raise ValueError("state_shardings requires mesh")
        # prefix pytree: one replicated sharding covers the metrics dict
        kw["out_shardings"] = (state_shardings, NamedSharding(mesh, P()))
    return jax.jit(step, donate_argnums=(0,), **kw)


def make_eval_step(model, has_batch_stats: bool = True):
    def step(state: TrainState, images: jax.Array, labels: jax.Array):
        variables = {"params": state.params}
        if has_batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, images, train=False)
        return {
            "loss": cross_entropy_loss(logits, labels),
            "accuracy": jnp.mean(jnp.argmax(logits, -1) == labels),
        }

    return jax.jit(step)


# ---------------------------------------------------------------------------
# Checkpointing (orbax) — SURVEY.md §5.4: resume = pod recreation with stable
# identity + framework-side restore; this is the framework side.
# ---------------------------------------------------------------------------


class Checkpointer:
    """Orbax-backed checkpoint manager.

    `async_save=True` overlaps the checkpoint write with training compute
    (orbax snapshots device arrays to host, then persists on a background
    thread) — the TPU-idiomatic mode: a multi-GB save costs one
    device-to-host copy instead of a full write stall.  Interval saves in
    the training loop then don't block the step; `wait_until_finished()`
    makes the last save durable before the process exits (preemption
    path)."""

    def __init__(
        self, directory: str, max_to_keep: int = 3, async_save: bool = False
    ) -> None:
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.async_save = async_save
        self.mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: TrainState, wait: bool = False) -> None:
        payload = {
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "batch_stats": state.batch_stats,
        }
        self.mngr.save(step, args=self._ocp.args.StandardSave(payload))
        if wait or not self.async_save:
            self.mngr.wait_until_finished()

    def wait_until_finished(self) -> None:
        """Block until every in-flight async save is durable on disk."""
        self.mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self.mngr.latest_step()

    def restore_params(self, params, step: Optional[int] = None):
        """Restore ONLY the params subtree — the inference path
        (examples/llama/generate_llama.py): a serving process has no
        optimizer, and demanding a matching opt_state tree just to read
        weights would tie checkpoint consumers to the trainer's
        optimizer choice."""
        step = step if step is not None else self.mngr.latest_step()
        if step is None:
            raise ValueError("no checkpoint to restore params from")
        restored = self.mngr.restore(
            step,
            args=self._ocp.args.PyTreeRestore(
                {"params": params}, partial_restore=True),
        )
        return restored["params"]

    def restore(self, state: TrainState, step: Optional[int] = None) -> TrainState:
        step = step if step is not None else self.mngr.latest_step()
        if step is None:
            return state
        payload = {
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "batch_stats": state.batch_stats,
        }
        restored = self.mngr.restore(
            step, args=self._ocp.args.StandardRestore(payload)
        )
        return state.replace(**restored)


# Step throughput bookkeeping lives in runtime/profiler.py (StepProfile).
