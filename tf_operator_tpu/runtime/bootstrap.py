"""In-container runtime bootstrap — the consumer side of the env contract.

The TPUJob controller injects COORDINATOR_ADDRESS / NUM_PROCESSES /
PROCESS_ID / TPU_* / MEGASCALE_* (controllers/tpu.py set_cluster_spec —
the TPU analogue of the TF_CONFIG the reference's containers read, SURVEY.md
§3.4). This module reads them back, initializes jax.distributed for
multi-host slices, and builds the device mesh. The e2e suite asserts this
round-trip the way the reference's estimator_runconfig_tests.py asserts
TF_CONFIG -> RunConfig.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

from tf_operator_tpu.parallel.mesh import make_mesh


@dataclass
class SliceInfo:
    """Parsed topology env for this host."""

    coordinator_address: Optional[str] = None
    megascale_coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    worker_id: int = 0
    worker_hostnames: tuple = ()
    accelerator_type: str = ""
    slice_id: int = 0
    num_slices: int = 1
    hosts_per_slice: int = 1
    total_hosts: int = 1
    topology: str = ""

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1 or self.num_slices > 1


def slice_info_from_env(env: Optional[Dict[str, str]] = None) -> SliceInfo:
    e = env if env is not None else os.environ
    hostnames = tuple(h for h in e.get("TPU_WORKER_HOSTNAMES", "").split(",") if h)
    return SliceInfo(
        coordinator_address=e.get("COORDINATOR_ADDRESS") or None,
        megascale_coordinator_address=e.get("MEGASCALE_COORDINATOR_ADDRESS") or None,
        num_processes=int(e.get("NUM_PROCESSES", "1")),
        process_id=int(e.get("PROCESS_ID", "0")),
        worker_id=int(e.get("TPU_WORKER_ID", "0")),
        worker_hostnames=hostnames,
        accelerator_type=e.get("TPU_ACCELERATOR_TYPE", ""),
        slice_id=int(e.get("TPU_SLICE_ID", "0")),
        num_slices=int(e.get("TPU_NUM_SLICES", e.get("MEGASCALE_NUM_SLICES", "1"))),
        hosts_per_slice=int(e.get("TPU_HOSTS_PER_SLICE", "1")),
        total_hosts=int(e.get("TPU_TOTAL_HOSTS", "1")),
        topology=e.get("TPU_TOPOLOGY", ""),
    )


_initialized = False


def global_rendezvous(info: SliceInfo):
    """(coordinator, num_processes, process_id) for jax.distributed.

    Multislice: jax.distributed is GLOBAL across all slices — one
    coordinator (slice 0, host 0 = the MEGASCALE address), global process
    count/id; the MEGASCALE_* env separately tells libtpu the slice
    topology for ICI-vs-DCN routing. Pure so the off-by-one-critical math
    (SURVEY.md §7.4.5) is unit-testable without jax.distributed."""
    if info.num_slices > 1:
        return (
            info.megascale_coordinator_address,
            info.total_hosts,
            info.slice_id * info.hosts_per_slice + info.process_id,
        )
    return info.coordinator_address, info.num_processes, info.process_id


def initialize(env: Optional[Dict[str, str]] = None) -> SliceInfo:
    """Initialize jax.distributed from the injected env (idempotent).
    Single-process jobs skip distributed init entirely."""
    global _initialized
    info = slice_info_from_env(env)
    if info.is_distributed and not _initialized:
        import jax

        coordinator, num_processes, process_id = global_rendezvous(info)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return info


def default_mesh(axes: Optional[Dict[str, int]] = None):
    """Mesh over all (global) devices; call after initialize(). Without
    `axes`, everything lands on the dp axis."""
    return make_mesh(axes=axes if axes is not None else {"dp": -1})


def multislice_mesh(
    info: SliceInfo,
    ici_axes: Optional[Dict[str, int]] = None,
    devices=None,
):
    """dcn×ici mesh for a (possibly) multislice job: one dcn row per slice,
    `ici_axes` (tp/fsdp/dp/ep/pp) laid out inside each slice.  Correct
    because jax orders devices by global process id and global_rendezvous
    assigns ids slice-major, so the contiguous dcn-outermost reshape in
    make_mesh puts each slice's chips in one dcn row — cross-slice traffic
    is whatever the caller maps to dcn (batch/gradients by DEFAULT_RULES),
    everything else stays on ICI.  Single-slice jobs get dcn=1 and this
    degenerates to default_mesh."""
    axes = dict(ici_axes if ici_axes is not None else {"dp": -1})
    if "dcn" in axes and axes["dcn"] not in (1, info.num_slices):
        raise ValueError(
            f"dcn axis {axes['dcn']} conflicts with numSlices {info.num_slices}"
        )
    axes["dcn"] = info.num_slices
    return make_mesh(axes=axes, devices=devices)
