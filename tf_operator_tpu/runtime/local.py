"""Local executor — run a job CR's replicas as REAL local subprocesses.

`tpu-jobs run-local job.yaml` (or `run_local(job_dict)`) stands up the
whole stack in one process — FakeCluster state store, OperatorManager
reconciling, and a SubprocessKubelet that materializes every created Pod
as an actual subprocess running the container's command with the
operator-injected env (TF_CONFIG, MASTER_*, TPU_*, ... —
docs/env_contract.md) — then waits for the job to reach a terminal
condition and returns its logs.

This is the dev-loop analogue of the reference's real-cluster e2e tier
(SURVEY.md §4.4): where the reference needs a live cluster + kubelet to
observe a replica's actual runtime config, run-local gives the same
observation from plain `python -c` / training scripts on the developer
machine. Cluster-internal DNS names (`{job}-{rt}-{i}.{ns}.svc`) are
rewritten to 127.0.0.1 in injected env values, so single-binder
rendezvous schemes (a jax.distributed coordinator on one port) work
locally; schemes where every replica binds the same port on its own
host (TF gRPC servers) need real pods.

Restart-policy decisions, status shapes, and the conflict-retrying
status write are shared with the in-process test-server kubelet
(e2e/kubelet.py) via k8s/kubelet_util.py.
"""
from __future__ import annotations

import os
import re
import shlex
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from tf_operator_tpu.k8s import kubelet_util, objects
from tf_operator_tpu.k8s.fake import FakeCluster, NotFoundError

# any cluster-internal service DNS form, with or without :port
_SVC_DNS = re.compile(r"[a-z0-9]([a-z0-9-]*[a-z0-9])?"
                      r"(-[0-9]+)?\.[a-z0-9-]+\.svc(\.[a-z0-9.-]*[a-z0-9])?")


def localize_env_value(value: str, job_name: str = "") -> str:
    """Rewrite cluster-internal hostnames to 127.0.0.1 (ports kept) so
    local processes can reach a locally-bound coordinator: the
    `{name}.{ns}.svc[.domain]` DNS form, and — given the pod's job name —
    the BARE headless-service names `{job}-{rtype}-{i}` that PyTorch's
    MASTER_ADDR and torchrun's PET_RDZV_ENDPOINT carry (reference
    pytorch.go:32-39 uses the plain service name).  The bare form is
    matched from the job name, not the live service list, so it cannot
    race service creation order; comma-separated rosters (LightGBM
    WORKER_ADDRS, TPU_WORKER_HOSTNAMES) localize element-wise."""
    value = _SVC_DNS.sub("127.0.0.1", value)
    if job_name:
        bare = re.compile(
            rf"^{re.escape(job_name)}-[a-z0-9]+-[0-9]+$"
        )
        parts = []
        for part in value.split(","):
            host, sep, port = part.partition(":")
            parts.append("127.0.0.1" + sep + port if bare.match(host) else part)
        value = ",".join(parts)
    return value


class _Proc:
    def __init__(self, popen: subprocess.Popen, container_name: str) -> None:
        self.popen = popen
        self.container_name = container_name
        self.restart_count = 0
        self.deleted = False


def _reap(popen: subprocess.Popen) -> None:
    """Kill + wait + close the pipe so no zombie survives."""
    popen.kill()
    try:
        popen.wait(timeout=5)
    except subprocess.TimeoutExpired:
        pass
    if popen.stdout is not None:
        popen.stdout.close()


class SubprocessKubelet:
    """Watches Pods on a cluster; runs each pod's first container command
    as a local subprocess, captures its output as the pod log, and drives
    pod phase/containerStatuses exactly like a kubelet."""

    def __init__(self, cluster: FakeCluster,
                 extra_env: Optional[Dict[str, str]] = None) -> None:
        self.cluster = cluster
        self.extra_env = dict(extra_env or {})
        self._lock = threading.Lock()
        self._running: Dict[str, _Proc] = {}
        self._shutdown = False
        cluster.subscribe("Pod", self._on_pod_event)

    # ------------------------------------------------------------- events
    def _on_pod_event(self, event_type: str, pod) -> None:
        key = objects.key_of(pod)
        if event_type == "ADDED":
            threading.Thread(
                target=self._start_pod, args=(key,), daemon=True
            ).start()
        elif event_type == "DELETED":
            self._stop_pod(key)

    # ---------------------------------------------------------- lifecycle
    def _argv_env(self, pod) -> Optional[tuple]:
        containers = pod.get("spec", {}).get("containers", [])
        if not containers:
            return None
        c = containers[0]
        argv = list(c.get("command") or []) + list(c.get("args") or [])
        if not argv:
            return None
        if argv[0] in ("python", "python3"):
            argv[0] = sys.executable  # the venv running the operator
        env = dict(os.environ)
        env.update(self.extra_env)
        job_name = objects.labels_of(pod).get(objects.LABEL_JOB_NAME, "")
        for e in c.get("env", []) or []:
            env[e["name"]] = localize_env_value(
                str(e.get("value", "")), job_name
            )
        return c.get("name", ""), argv, env

    def _start_pod(self, key: str) -> None:
        namespace, _, name = key.partition("/")
        try:
            pod = self.cluster.get_pod(namespace, name)
        except NotFoundError:
            return
        spec = self._argv_env(pod)
        if spec is None:
            self.cluster.append_pod_log(
                namespace, name, "run-local: container has no command; "
                "local pods must specify command/args")
            self._mark_terminal(key, "", 127, restart_count=0)
            return
        container_name, argv, env = spec
        self._spawn(key, container_name, argv, env, restart_count=0)

    def _spawn(self, key: str, container_name: str, argv: List[str],
               env: Dict[str, str], restart_count: int) -> None:
        namespace, _, name = key.partition("/")
        with self._lock:
            if self._shutdown:
                return
        try:
            popen = subprocess.Popen(
                argv, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        except OSError as e:
            self.cluster.append_pod_log(namespace, name, f"spawn failed: {e}")
            self._mark_terminal(key, container_name, 127, restart_count)
            return
        proc = _Proc(popen, container_name)
        proc.restart_count = restart_count
        with self._lock:
            # losing a registration race (duplicate ADDED) or a shutdown
            # that began mid-spawn: reap the redundant child, no zombies
            if self._shutdown or key in self._running:
                reap = True
            else:
                self._running[key] = proc
                reap = False
        if reap:
            _reap(popen)
            return
        self.cluster.append_pod_log(
            namespace, name,
            f"container {container_name} started: {shlex.join(argv)}")
        self._write_status(
            namespace, name,
            lambda pod: kubelet_util.mark_running(
                pod, container_name, restart_count))
        threading.Thread(
            target=self._pump, args=(key, proc, argv, env), daemon=True
        ).start()

    def _pump(self, key: str, proc: _Proc, argv: List[str],
              env: Dict[str, str]) -> None:
        namespace, _, name = key.partition("/")
        for line in proc.popen.stdout:  # drains until EOF (process exit)
            self.cluster.append_pod_log(namespace, name, line.rstrip("\n"))
        code = proc.popen.wait()
        proc.popen.stdout.close()
        with self._lock:
            current = self._running.get(key)
            if current is not proc:
                return  # superseded
            self._running.pop(key, None)
            if proc.deleted or self._shutdown:
                return  # torn down; do not respawn or write status
        try:
            pod = self.cluster.get_pod(namespace, name)
        except NotFoundError:
            return
        policy = pod.get("spec", {}).get("restartPolicy", "Always")
        if kubelet_util.should_restart(policy, code):
            # kubelet-style in-place restart: same pod object, count++
            count = proc.restart_count + 1
            self.cluster.append_pod_log(
                namespace, name, f"restarting container (count {count})")
            ok = self._write_status(
                namespace, name,
                lambda pod: kubelet_util.mark_restarting(
                    pod, proc.container_name, count, code))
            if ok:
                self._spawn(key, proc.container_name, argv, env, count)
            return
        self._mark_terminal(key, proc.container_name, code,
                            proc.restart_count)

    def _mark_terminal(self, key: str, container_name: str, code: int,
                       restart_count: int) -> None:
        namespace, _, name = key.partition("/")
        self._write_status(
            namespace, name,
            lambda pod: kubelet_util.mark_terminal(
                pod, container_name, code, restart_count))

    def _write_status(self, namespace: str, name: str, mutate) -> bool:
        return kubelet_util.write_pod_status(
            self.cluster, namespace, name, mutate)

    def _stop_pod(self, key: str) -> None:
        with self._lock:
            proc = self._running.pop(key, None)
            if proc is not None:
                proc.deleted = True
        if proc is not None:
            proc.popen.terminate()
            try:
                proc.popen.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.popen.kill()

    def stop_all(self) -> None:
        # the flag (checked under the same lock _pump/_spawn hold) closes
        # the restart race: a crash-looping pod mid-respawn during
        # shutdown must not leave an orphan process behind
        with self._lock:
            self._shutdown = True
            keys = list(self._running)
        for key in keys:
            self._stop_pod(key)


# ------------------------------------------------------------------ driver
def run_local(job: Dict[str, Any], timeout: float = 300.0,
              poll: float = 0.2,
              extra_env: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Run one job CR end to end locally. Returns {"job": final_cr,
    "state": str, "timed_out": bool, "logs": {pod_name: text}} — state is
    "Timeout" when the deadline fired before a terminal condition (the
    last observed phase is still in the returned job's conditions)."""
    from tf_operator_tpu.api import common
    from tf_operator_tpu.cmd.manager import OperatorManager
    from tf_operator_tpu.cmd.options import ServerOptions
    from tf_operator_tpu.sdk.watch import job_state

    kind = job.get("kind", "")
    namespace = job.get("metadata", {}).get("namespace", "default")
    name = job.get("metadata", {}).get("name", "")
    cluster = FakeCluster()
    kubelet = SubprocessKubelet(cluster, extra_env=extra_env)
    manager = OperatorManager(cluster, ServerOptions())
    manager.start()
    try:
        cluster.create(kind, job)
        deadline = time.monotonic() + timeout
        timed_out = True
        while time.monotonic() < deadline:
            cr = cluster.get(kind, namespace, name)
            if job_state(cr) in (common.JOB_SUCCEEDED, common.JOB_FAILED):
                timed_out = False
                break
            time.sleep(poll)
        cr = cluster.get(kind, namespace, name)
        state = "Timeout" if timed_out else job_state(cr)
        return {
            "job": cr,
            "state": state,
            "timed_out": timed_out,
            "logs": cluster.all_pod_logs(namespace),
        }
    finally:
        kubelet.stop_all()
        manager.stop()
