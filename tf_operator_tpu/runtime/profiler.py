"""Profiling/tracing subsystem — XLA/JAX profiler hooks + per-step metrics.

The reference has no in-operator tracing (SURVEY.md §5.1: observability is
metrics + logs + events; cAdvisor for container stats). On TPU the profiler
is first-class: `jax.profiler` captures device traces (MXU utilization,
HBM transfers, ICI collectives) viewable in TensorBoard/XProf, and the
per-step wall-clock stream is the operator's throughput signal.

Pieces:
  - `StepProfile`: ring-buffer of per-step wall times -> steps/sec, p50/p99.
  - `annotate_step(n)`: StepTraceAnnotation so device traces align to steps.
  - `Profiler`: programmatic trace capture (start/stop or N-step window),
    plus a metrics-line emitter the runner ships to stdout for scraping
    (the analogue of the reference's prometheus counters, SURVEY.md §5.5).
"""
from __future__ import annotations

import json
import math
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

import jax


def annotate_step(step: int):
    """Context manager marking one train step in the device trace
    (jax.profiler.StepTraceAnnotation)."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@dataclass
class StepProfile:
    """Per-step wall-time stats over a sliding window."""

    window: int = 200
    _times: List[float] = field(default_factory=list)
    _last: Optional[float] = None

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
            if len(self._times) > self.window:
                self._times.pop(0)
        self._last = now

    def reset(self) -> None:
        self._times.clear()
        self._last = None

    @property
    def steps_recorded(self) -> int:
        return len(self._times)

    def steps_per_sec(self) -> float:
        if not self._times:
            return 0.0
        return len(self._times) / sum(self._times)

    def percentile(self, q: float) -> float:
        """q-th percentile step time in seconds (q in [0, 100])."""
        if not self._times:
            return 0.0
        xs = sorted(self._times)
        idx = min(len(xs) - 1, max(0, math.ceil(q / 100.0 * len(xs)) - 1))
        return xs[idx]

    def summary(self, batch_size: Optional[int] = None) -> Dict[str, float]:
        s: Dict[str, float] = {
            "steps_per_sec": self.steps_per_sec(),
            "step_time_p50_ms": self.percentile(50) * 1e3,
            "step_time_p99_ms": self.percentile(99) * 1e3,
        }
        if batch_size is not None:
            s["examples_per_sec"] = self.steps_per_sec() * batch_size
        return s


class Profiler:
    """Programmatic jax.profiler capture + metrics emission.

    `trace_dir` enables device-trace capture; without it the profiler still
    tracks step stats (zero-overhead in the hot loop beyond a perf_counter
    read per step)."""

    def __init__(
        self,
        trace_dir: Optional[str] = None,
        batch_size: Optional[int] = None,
        window: int = 200,
        trace_start_step: int = 10,
        trace_num_steps: int = 20,
    ) -> None:
        self.trace_dir = trace_dir
        self.batch_size = batch_size
        self.steps = StepProfile(window=window)
        self.trace_start_step = trace_start_step
        self.trace_num_steps = trace_num_steps
        self._tracing = False
        self._trace_started_at: Optional[int] = None
        self._trace_done = False

    # ------------------------------------------------------------- tracing
    def start_trace(self) -> None:
        if self.trace_dir and not self._tracing:
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def stop_trace(self) -> None:
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False

    @contextmanager
    def trace_window(self) -> Iterator[None]:
        """Capture a device trace for the enclosed steps."""
        self.start_trace()
        try:
            yield
        finally:
            self.stop_trace()

    def maybe_trace(self, step: int) -> None:
        """Bounded-window capture driven by the training loop: with a
        trace_dir set, start once the step counter passes trace_start_step
        (>= — a checkpoint-resumed run whose first step is already past
        the threshold still gets its window) and stop after
        trace_num_steps, exactly once per process.  No-op otherwise; the
        loop's final stop_trace() flushes an unfinished window on early
        exit/preemption."""
        if not self.trace_dir or self._trace_done:
            return
        if not self._tracing:
            if step >= self.trace_start_step:
                self.start_trace()
                self._trace_started_at = step
        elif self._trace_started_at is None:
            # the window was opened externally (trace_window()/start_trace()
            # around the whole run) — adopt the current step as its origin
            # so the bounded stop below still applies instead of crashing
            # on None arithmetic
            self._trace_started_at = step
        elif step >= self._trace_started_at + self.trace_num_steps:
            self.stop_trace()
            self._trace_done = True

    @contextmanager
    def step(self, n: int) -> Iterator[None]:
        """Wrap one train step: trace annotation + wall-time tick."""
        with annotate_step(n):
            yield
        self.steps.tick()

    # ------------------------------------------------------------- metrics
    def metrics_line(self, step: int, extra: Optional[Dict] = None) -> str:
        """One JSON line of progress metrics (shipped to stdout; the
        in-container analogue of the operator's prometheus counters)."""
        payload = {"step": step, **self.steps.summary(self.batch_size)}
        if extra:
            payload.update(
                {
                    k: (float(v) if hasattr(v, "item") else v)
                    for k, v in extra.items()
                }
            )
        return json.dumps(payload)


def device_memory_stats() -> Dict[str, int]:
    """Per-device HBM usage {device: bytes_in_use} where the backend exposes
    it (TPU/GPU; CPU returns {})."""
    out: Dict[str, int] = {}
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats and "bytes_in_use" in stats:
            out[str(d)] = int(stats["bytes_in_use"])
    return out
