"""Profiling/tracing subsystem — XLA/JAX profiler hooks + per-step metrics.

The reference has no in-operator tracing (SURVEY.md §5.1: observability is
metrics + logs + events; cAdvisor for container stats). On TPU the profiler
is first-class: `jax.profiler` captures device traces (MXU utilization,
HBM transfers, ICI collectives) viewable in TensorBoard/XProf, and the
per-step wall-clock stream is the operator's throughput signal.

Pieces:
  - `StepProfile`: ring-buffer of per-step wall times -> steps/sec, p50/p99.
  - `annotate_step(n)`: StepTraceAnnotation so device traces align to steps.
  - `GoodputTracker`: splits wall-clock into productive step time vs
    checkpoint-save, resume-replay, and idle time, plus an MFU estimate
    from a caller-supplied FLOPs-per-step — the measured throughput signal
    heterogeneity-aware schedulers assume the training system can report
    (Gavel, arxiv 2008.09213; Tesserae, arxiv 2508.04953).
  - `Profiler`: programmatic trace capture (start/stop or N-step window),
    plus a metrics-line emitter the runner ships to stdout for scraping
    (the analogue of the reference's prometheus counters, SURVEY.md §5.5).
"""
from __future__ import annotations

import json
import math
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterator, Optional

import jax


def annotate_step(step: int):
    """Context manager marking one train step in the device trace
    (jax.profiler.StepTraceAnnotation)."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@dataclass
class StepProfile:
    """Per-step wall-time stats over a sliding window.

    The window is a deque(maxlen=window): appending past capacity drops
    the oldest in O(1), where a list + pop(0) shifted the whole window
    every step in the hot loop."""

    window: int = 200
    _times: Deque[float] = field(default_factory=deque)
    _last: Optional[float] = None

    def __post_init__(self) -> None:
        self._times = deque(self._times, maxlen=self.window)

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
        self._last = now

    def reset(self) -> None:
        self._times.clear()
        self._last = None

    @property
    def steps_recorded(self) -> int:
        return len(self._times)

    def steps_per_sec(self) -> float:
        if not self._times:
            return 0.0
        return len(self._times) / sum(self._times)

    def percentile(self, q: float) -> float:
        """q-th percentile step time in seconds (q in [0, 100])."""
        if not self._times:
            return 0.0
        xs = sorted(self._times)
        idx = min(len(xs) - 1, max(0, math.ceil(q / 100.0 * len(xs)) - 1))
        return xs[idx]

    def summary(self, batch_size: Optional[int] = None) -> Dict[str, float]:
        s: Dict[str, float] = {
            "steps_per_sec": self.steps_per_sec(),
            "step_time_p50_ms": self.percentile(50) * 1e3,
            "step_time_p99_ms": self.percentile(99) * 1e3,
        }
        if batch_size is not None:
            s["examples_per_sec"] = self.steps_per_sec() * batch_size
        return s


class GoodputTracker:
    """Wall-clock accounting: productive vs checkpoint vs replay vs idle.

    "Goodput" is the fraction of elapsed wall-clock spent making forward
    progress (running train steps). The rest is attributed to
    checkpoint-save stalls, resume-replay (restoring state after a
    recreation), or idle (input pipeline, host callbacks, anything
    unaccounted). The training loop (runtime/loop.py) owns the exact
    boundaries — it wraps restore and save calls in the context managers
    below — so the split is measured, not inferred.

    MFU: with a caller-supplied `flops_per_step` (model FLOPs, not
    hardware FLOPs) and the accelerator's `peak_flops_per_sec`, `mfu()`
    reports achieved-model-FLOPs / peak over total wall-clock — the
    standard Model FLOPs Utilization definition, which charges every
    non-step second against utilization."""

    def __init__(
        self,
        flops_per_step: Optional[float] = None,
        peak_flops_per_sec: Optional[float] = None,
    ) -> None:
        self.flops_per_step = flops_per_step
        self.peak_flops_per_sec = peak_flops_per_sec
        self.productive_time = 0.0
        self.checkpoint_time = 0.0
        self.replay_time = 0.0
        self.steps = 0
        self._start: Optional[float] = None
        self._end: Optional[float] = None

    # ------------------------------------------------------------ recording
    def start(self) -> None:
        """Start the wall clock (idempotent; note_* auto-start). Starting
        again after stop() resumes the clock, excluding the paused gap —
        a profiler reused across run_training sessions must not charge
        the time between sessions as idle."""
        now = time.perf_counter()
        if self._start is None:
            self._start = now
        elif self._end is not None:
            self._start += now - self._end
        self._end = None

    def stop(self) -> None:
        """Freeze the wall clock (end of the training session)."""
        if self._start is not None and self._end is None:
            self._end = time.perf_counter()

    def note_productive(self, duration: float, steps: int = 1) -> None:
        self.start()
        self.productive_time += duration
        self.steps += steps

    @contextmanager
    def checkpoint_save(self) -> Iterator[None]:
        """Wrap a (blocking portion of a) checkpoint save."""
        self.start()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.checkpoint_time += time.perf_counter() - t0

    @contextmanager
    def resume_replay(self) -> Iterator[None]:
        """Wrap checkpoint-restore / replay work done to resume a run."""
        self.start()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.replay_time += time.perf_counter() - t0

    # ------------------------------------------------------------- derived
    def wall_time(self) -> float:
        if self._start is None:
            return 0.0
        return (self._end or time.perf_counter()) - self._start

    def goodput(self) -> float:
        wall = self.wall_time()
        return self.productive_time / wall if wall > 0 else 0.0

    def mfu(self) -> Optional[float]:
        """Model FLOPs Utilization over total wall-clock; None until both
        flops_per_step and peak_flops_per_sec are known and a step ran."""
        wall = self.wall_time()
        if (
            self.flops_per_step is None
            or not self.peak_flops_per_sec
            or self.steps == 0
            or wall <= 0
        ):
            return None
        return (self.flops_per_step * self.steps / wall) / self.peak_flops_per_sec

    def summary(self) -> Dict[str, float]:
        wall = self.wall_time()
        if wall <= 0:
            return {}
        accounted = self.productive_time + self.checkpoint_time + self.replay_time
        s = {
            "wall_time_s": wall,
            "goodput": self.productive_time / wall,
            "productive_fraction": self.productive_time / wall,
            "checkpoint_fraction": self.checkpoint_time / wall,
            "replay_fraction": self.replay_time / wall,
            "idle_fraction": max(0.0, (wall - accounted) / wall),
        }
        mfu = self.mfu()
        if mfu is not None:
            s["mfu"] = mfu
        return s


def _json_safe(v):
    """JSON scalars only: device arrays -> float, non-finite floats -> None
    (bare NaN/Inf is invalid JSON and breaks scrapers)."""
    if hasattr(v, "item"):
        v = float(v)
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


class Profiler:
    """Programmatic jax.profiler capture + metrics emission.

    `trace_dir` enables device-trace capture; without it the profiler still
    tracks step stats (zero-overhead in the hot loop beyond a perf_counter
    read per step)."""

    def __init__(
        self,
        trace_dir: Optional[str] = None,
        batch_size: Optional[int] = None,
        window: int = 200,
        trace_start_step: int = 10,
        trace_num_steps: int = 20,
        flops_per_step: Optional[float] = None,
        peak_flops_per_sec: Optional[float] = None,
    ) -> None:
        self.trace_dir = trace_dir
        self.batch_size = batch_size
        self.steps = StepProfile(window=window)
        self.goodput = GoodputTracker(
            flops_per_step=flops_per_step,
            peak_flops_per_sec=peak_flops_per_sec,
        )
        self.trace_start_step = trace_start_step
        self.trace_num_steps = trace_num_steps
        self._tracing = False
        self._trace_started_at: Optional[int] = None
        self._trace_done = False

    # ------------------------------------------------------------- tracing
    def start_trace(self) -> None:
        if self.trace_dir and not self._tracing:
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def stop_trace(self) -> None:
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False

    @contextmanager
    def trace_window(self) -> Iterator[None]:
        """Capture a device trace for the enclosed steps."""
        self.start_trace()
        try:
            yield
        finally:
            self.stop_trace()

    def maybe_trace(self, step: int) -> None:
        """Bounded-window capture driven by the training loop: with a
        trace_dir set, start once the step counter passes trace_start_step
        (>= — a checkpoint-resumed run whose first step is already past
        the threshold still gets its window) and stop after
        trace_num_steps, exactly once per process.  No-op otherwise; the
        loop's final stop_trace() flushes an unfinished window on early
        exit/preemption."""
        if not self.trace_dir or self._trace_done:
            return
        if not self._tracing:
            if step >= self.trace_start_step:
                self.start_trace()
                self._trace_started_at = step
        elif self._trace_started_at is None:
            # the window was opened externally (trace_window()/start_trace()
            # around the whole run) — adopt the current step as its origin
            # so the bounded stop below still applies instead of crashing
            # on None arithmetic
            self._trace_started_at = step
        elif step >= self._trace_started_at + self.trace_num_steps:
            self.stop_trace()
            self._trace_done = True

    @contextmanager
    def step(self, n: int) -> Iterator[None]:
        """Wrap one train step: trace annotation + wall-time tick +
        productive-time attribution for the goodput split."""
        t0 = time.perf_counter()
        with annotate_step(n):
            yield
        self.steps.tick()
        self.goodput.note_productive(time.perf_counter() - t0)

    # ------------------------------------------------------------- metrics
    def summary(self) -> Dict[str, float]:
        """Step-time stats + the goodput/MFU split, one flat dict."""
        return {**self.steps.summary(self.batch_size), **self.goodput.summary()}

    def metrics_line(self, step: int, extra: Optional[Dict] = None) -> str:
        """One JSON line of progress metrics (shipped to stdout; the
        in-container analogue of the operator's prometheus counters).
        Non-finite floats (a NaN loss) serialize as null — bare NaN is
        invalid JSON and breaks scrapers."""
        payload = {"step": step, **self.summary()}
        if extra:
            payload.update(extra)
        return json.dumps({k: _json_safe(v) for k, v in payload.items()})


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device HBM usage where the backend exposes it (TPU/GPU; CPU
    returns {} — backends without memory_stats never grow keys).

    {device: {"bytes_in_use": N[, "peak_bytes_in_use": N,
              "bytes_limit": N]}} — the peak is the allocation high
    watermark since process start (the number a serving run's headroom
    question actually needs: a transient prefill spike never shows in
    an end-of-run bytes_in_use read), and bytes_limit is the device's
    allocatable ceiling; both ride along only when the PJRT backend
    reports them (TPU and GPU do today)."""
    out: Dict[str, Dict[str, int]] = {}
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats and "bytes_in_use" in stats:
            entry = {"bytes_in_use": int(stats["bytes_in_use"])}
            for key in ("peak_bytes_in_use", "bytes_limit"):
                if key in stats:
                    entry[key] = int(stats[key])
            out[str(d)] = entry
    return out
