"""Pallas paged-attention decode kernel — block-indexed KV, no gather.

PR 9's block pool made paged serving a MEMORY win; this kernel makes it
a SPEED win.  The gather path (models/paging.gather_blocks) materializes
a per-lane LINEAR view of the cache — `pool[table]` — before running the
unchanged dense attention, which on a real TPU is a full cache-sized
HBM gather per generated token.  This kernel consumes the block pool
`[N+1, bs, KV, D]` and the per-lane block tables DIRECTLY:

  - grid over (lane, kv_head, table slot): the table rides as a
    SCALAR-PREFETCH operand (pltpu.PrefetchScalarGridSpec), so the K/V
    BlockSpec index maps resolve `table[lane, slot]` BEFORE each grid
    step and pallas's double-buffered pipeline DMAs exactly that one
    block from HBM into VMEM — blocks stream through VMEM in table
    order, and no linear K/V copy ever exists.
  - ONLINE SOFTMAX across the streamed blocks (the flash-attention
    recipe, one block at a time): running max / running sum / f32
    accumulator live in VMEM scratch that persists across the table
    dimension, finalized at the last slot.
  - the SCRATCH block (id 0, models/paging.SCRATCH_BLOCK) contributes
    masked -inf scores: frozen lanes (all-scratch tables) and table
    padding need no special casing — an all-masked row finalizes
    through the l==0 guard to a finite zero vector, which the serve
    loop's frozen-lane token mask discards anyway.
  - POSITION VISIBILITY is the dense ring formula verbatim
    (llama._cached_attention): slot position `t*bs + off` resolves to
    global position `q - mod(q - slot_pos, ring)` with `ring = T*bs`.
    For linear tables (ring >= every position) that is exactly
    "written and causal"; for MODULAR window tables (serve_loop paged
    sliding-window) the same formula handles the wrap seam, and the
    optional `window` mask hides out-of-band positions — one kernel,
    both table disciplines, parity with dense by the same argument the
    gather path makes.
  - GQA is native: one grid program owns one kv head and contracts its
    whole query group [L*G, D] against each [bs, D] block — the shared
    kv head is read once, never repeated.
  - int8 KV pools (models/quant.QTensor leaves) dequantize IN the
    kernel, per block: payload and per-(position, head) scales ride
    separate BlockSpecs through the same table index map, so int8 is
    what streams from HBM — the same contract as the dense ring's
    fused dequant.

MULTI-TOKEN q (the chunked-prefill / speculative-verify contraction) is
the same kernel at L > 1: query rows become [L*G, bs] score tiles with
per-row positions `base + l` (positions are consecutive on every paged
write path).  `_MAX_Q_ROWS` bounds the VMEM the q tile may take; above
it callers fall back to the gather path (prefill is MXU-bound, not
gather-bound, so nothing is lost).

On CPU the kernel runs under `interpret=True` (the flash kernel's
convention), which is how the tier-1 parity matrix pins
token-identity to the dense ring without TPU hardware; the gather path
remains selectable (`paged_kernel="gather"`) as the oracle.

No reference counterpart (the reference has no serving code at all,
SURVEY.md §5.7).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# q tile rows (L * group) beyond which the caller should prefer the
# gather path: the kernel holds q [rows, D], the accumulator [rows, D]
# and a [rows, bs] score tile in VMEM — at 1024 rows x D=128 that is
# ~1.5 MB f32, comfortably inside the ~16 MB budget; a 8k-token prefill
# chunk would not be.
_MAX_Q_ROWS = 1024


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params(interpret: bool):
    """lane and kv-head grid dims are parallel (disjoint outputs); the
    streamed table dim is sequential (scratch carries the softmax state
    across it)."""
    if interpret:
        return None
    try:
        return pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    except Exception:  # older pallas: run without the hint
        return None


def _kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, *, bs: int, group: int, n_slots: int,
            ring: int, window: Optional[int], scale: float,
            k_scale_ref=None, v_scale_ref=None):
    """One (lane, kv_head, table slot) step: score q's group rows
    against the slot's block, mask by visibility + scratch, fold into
    the online-softmax accumulators; finalize at the last slot."""
    b, t = pl.program_id(0), pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    block_id = tbl_ref[b, t]
    q = q_ref[0, 0]                                   # [LG, D]
    k = k_ref[0, :, 0, :]                              # [bs, D]
    v = v_ref[0, :, 0, :]
    if k_scale_ref is not None:
        # int8 pool: dequantize the block in VMEM, exactly the dense
        # read's math (QTensor.dequantize: f32 payload * scale -> dtype)
        k = (k.astype(jnp.float32)
             * k_scale_ref[0, :, 0, :]).astype(q.dtype)
        v = (v.astype(jnp.float32)
             * v_scale_ref[0, :, 0, :]).astype(q.dtype)
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [LG, bs]
    # per-row query position: rows are (l, g) with position base + l —
    # every paged write path produces consecutive positions, so the
    # scalar base per lane is the whole story
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
    q_pos = pos_ref[b] + rows                          # [LG, bs]
    slot_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + t * bs
    # the dense ring visibility formula (llama._cached_attention): a
    # slot's last-written global position; negative = unwritten, and
    # for linear tables (ring >= every position) this reduces to
    # slot_pos <= q_pos — written-and-causal
    k_global = q_pos - jnp.mod(q_pos - slot_pos, ring)
    mask = k_global >= 0
    if window is not None:
        mask &= k_global > q_pos - window
    mask &= block_id != 0  # scratch: frozen lanes / table padding
    s = jnp.where(mask, s, NEG_INF)
    m_prev = m_scr[:, 0]
    l_prev = l_scr[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    # zero masked probabilities EXPLICITLY: while the running max is
    # still NEG_INF (a fully-masked prefix of the table — frozen lane,
    # or every block so far outside the window band), exp(s - m) would
    # be exp(0) = 1 and the row would finalize to an average of
    # garbage V instead of through the l == 0 guard below; once a real
    # score has been seen, masked entries underflow to 0 anyway and
    # this is a no-op
    p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_scr[:, 0] = l_prev * corr + jnp.sum(p, axis=1)
    m_scr[:, 0] = m_new
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_scr[:] = acc_scr[:] * corr[:, None] + pv

    @pl.when(t == n_slots - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # all-masked (frozen) -> 0
        o_ref[0, 0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, table, pos, *,
                    window: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Block-indexed paged attention.

    q:            [B, L, H, D] post-RoPE queries (L new positions).
    k_pool/v_pool:[N+1, bs, KV, D] block pools (models/paging
                  .init_block_pool; id 0 = scratch), or QTensor pools
                  (int8 payload + per-(position, head) f32 scales).
    table:        [B, T] int32 per-lane block tables (position p lives
                  in table[p // bs] for linear tables, table[(p // bs)
                  % T] for modular window tables — the kernel's ring
                  formula covers both).
    pos:          scalar or [B] int32 — global position of q[:, 0];
                  row l attends positions visible to `pos + l`.
    window:       sliding-window width (cfg.sliding_window); None =
                  full causal.

    Returns [B, L, H, D], numerically the gather path's
    `_cached_attention(q, gather_blocks(k), gather_blocks(v), ...)`
    computed without ever materializing the linear view.
    """
    from tf_operator_tpu.models.quant import QTensor

    b, l, h, d = q.shape
    quantized = isinstance(k_pool, QTensor)
    kv_heads = (k_pool.q if quantized else k_pool).shape[2]
    bs = (k_pool.q if quantized else k_pool).shape[1]
    if h % kv_heads:
        raise ValueError(
            f"q heads {h} not divisible by kv heads {kv_heads}")
    group = h // kv_heads
    n_slots = table.shape[1]
    ring = n_slots * bs
    lg = l * group
    if interpret is None:
        interpret = _use_interpret()
    scale = 1.0 / (d ** 0.5)
    if getattr(pos, "ndim", 0) == 0:
        pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    pos = pos.astype(jnp.int32)

    # [B, L, H, D] -> [B, KV, L*G, D]: one grid program owns one kv
    # head's whole query group; rows are (l, g) pairs, row // G = l
    q3 = (q.reshape(b, l, kv_heads, group, d)
          .transpose(0, 2, 1, 3, 4)
          .reshape(b, kv_heads, lg, d))

    num_prefetch = 2  # table + positions resolve index maps pre-DMA
    q_spec = pl.BlockSpec((1, 1, lg, d), lambda i, j, t, tbl, p: (i, j, 0, 0))
    blk_spec = pl.BlockSpec(
        (1, bs, 1, d), lambda i, j, t, tbl, p: (tbl[i, t], 0, j, 0))
    in_specs = [q_spec, blk_spec, blk_spec]
    args = [table, pos, q3]
    if quantized:
        scl_spec = pl.BlockSpec(
            (1, bs, 1, 1), lambda i, j, t, tbl, p: (tbl[i, t], 0, j, 0))
        in_specs += [scl_spec, scl_spec]
        args += [k_pool.q, v_pool.q, k_pool.scale, v_pool.scale]
        kern = functools.partial(
            _int8_kernel_adapter, bs=bs, group=group, n_slots=n_slots,
            ring=ring, window=window, scale=scale)
    else:
        args += [k_pool, v_pool]
        kern = functools.partial(
            _kernel, bs=bs, group=group, n_slots=n_slots, ring=ring,
            window=window, scale=scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=num_prefetch,
        grid=(b, kv_heads, n_slots),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, lg, d), lambda i, j, t, tbl, p: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((lg, 1), jnp.float32),   # running max
            pltpu.VMEM((lg, 1), jnp.float32),   # running sum
            pltpu.VMEM((lg, d), jnp.float32),   # output accumulator
        ],
    )
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv_heads, lg, d), q.dtype),
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(*args)
    return (out.reshape(b, kv_heads, l, group, d)
            .transpose(0, 2, 1, 3, 4)
            .reshape(b, l, h, d))


def _int8_kernel_adapter(tbl_ref, pos_ref, q_ref, k_ref, v_ref,
                         k_scale_ref, v_scale_ref, o_ref,
                         m_scr, l_scr, acc_scr, **kw):
    """Ref-order shim: pallas passes scale refs after the payload refs
    and before the output; the core kernel takes them by keyword."""
    _kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr,
            k_scale_ref=k_scale_ref, v_scale_ref=v_scale_ref, **kw)


def fits_kernel(l: int, n_heads: int, n_kv_heads: int) -> bool:
    """Whether an L-token contraction's q tile fits the kernel's VMEM
    budget (the chunked-prefill variant is the same kernel at L > 1);
    callers fall back to the gather path above the bound."""
    return l * (n_heads // n_kv_heads) <= _MAX_Q_ROWS


def ragged_step_on_kernel(seg_len: int, n_heads: int,
                          n_kv_heads: int) -> bool:
    """Ragged step entry (ISSUE 19): the continuous scheduler's fused
    dispatch carries B decode rows of one token each PLUS one prefill
    row of `seg_len` tokens over the same block pool
    (models/serving._cb_paged_serve_fns).  Each row class reaches this
    module as its own contraction — decode rows at L=1, the segment at
    L=seg_len — and llama's attention falls back to the gather oracle
    PER CALL when a tile overflows, so fusion is always correct; this
    predicate says whether the WHOLE ragged step stays on the pallas
    path (the perf planning question: a fused step whose prefill side
    drops to gather still saves the dispatch, not the kernel).  Use it
    to pick a prefill_chunk that keeps fused steps kernel-resident."""
    return (fits_kernel(1, n_heads, n_kv_heads)
            and fits_kernel(seg_len, n_heads, n_kv_heads))
