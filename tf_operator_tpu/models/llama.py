"""LLaMA-class decoder LM — RoPE + GQA + SwiGLU + RMSNorm.

Beyond-reference [+]: the reference's ladder tops out at BERT-large and
T5-3B (SURVEY.md §6; reference examples only ship estimator/Keras-era
models); this adds the modern decoder family so the framework covers the
architectures users actually train today, wired to the same TPU seams as
models/transformer.py:

- attention is pluggable through the (q, k, v, causal) contract, so the
  pallas flash kernel (ops/flash_attention.py), ring sequence parallelism
  (ops/ring_attention.py), and Ulysses all drop in; RoPE is applied BEFORE
  the attention_fn, so every backend sees post-rotary q/k and needs no
  position awareness of its own.
- rotary embeddings take explicit `positions` ids — the seam the zigzag
  causal ring layout (ops/zigzag.py) uses to permute tokens while keeping
  each token's rotation tied to its global position.
- GQA shares one K/V head across `n_heads // n_kv_heads` query heads; the
  kv heads are broadcast to full head count just before the attention
  contraction (inside the jit — XLA commonly fuses the broadcast into the
  first score matmul, and the projection/grad savings, which is where GQA
  helps a *training* step, are realized regardless).
- bf16 compute / f32 params, static shapes, fused [2, F] SwiGLU gate+up
  matmul and fused [2, KV, D] K/V projection (fewer, larger MXU calls).
- `return_hidden` exposes the pre-logits hidden states so
  ops/blocked_ce.py can fuse the lm-head matmul into the loss without a
  [B, S, V] materialization at large vocab.

Sharding: parallel/tp.py places wq/wkv column-parallel over tp, attention
out and SwiGLU wo row-parallel, embedding vocab-parallel — one tp
all-reduce per block, same rule table as the transformer family.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_heads: int = 32
    n_kv_heads: int = 8
    n_layers: int = 32
    d_ff: int = 11008
    max_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    # None -> reference einsum; or ops/flash_attention.flash_attention /
    # ops/ring_attention.make_ring_attention_fn(...) — called with
    # post-RoPE (q, k, v, causal=True)
    attention_fn: Optional[Callable] = None
    remat: bool = False  # jax.checkpoint each block

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError(
                f"d_model {self.d_model} not divisible by n_heads {self.n_heads}"
            )
        if self.n_heads % self.n_kv_heads:
            raise ValueError(
                f"n_heads {self.n_heads} not divisible by "
                f"n_kv_heads {self.n_kv_heads}"
            )
        if self.head_dim % 2:
            raise ValueError(f"head_dim {self.head_dim} must be even for RoPE")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads


def _config(base: dict, kw: dict) -> LlamaConfig:
    base.update(kw)
    return LlamaConfig(**base)


def llama_7b(**kw) -> LlamaConfig:
    """7B-class: MHA-era layout (n_kv_heads == n_heads)."""
    return _config(dict(
        vocab_size=32000, d_model=4096, n_heads=32, n_kv_heads=32,
        n_layers=32, d_ff=11008, max_len=2048,
    ), kw)


def llama3_8b(**kw) -> LlamaConfig:
    """8B-class: GQA 4:1, larger vocab, theta=500k long-context base."""
    return _config(dict(
        vocab_size=128256, d_model=4096, n_heads=32, n_kv_heads=8,
        n_layers=32, d_ff=14336, max_len=8192, rope_theta=500000.0,
    ), kw)


def tiny(**kw) -> LlamaConfig:
    return _config(dict(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2,
        n_layers=2, d_ff=128, max_len=64,
    ), kw)


# ------------------------------------------------------------------ rotary
def rope_table(max_len: int, head_dim: int, theta: float) -> jax.Array:
    """[max_len, head_dim/2] rotation angles: pos / theta^(2i/d)."""
    inv_freq = theta ** (
        -jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    )
    return jnp.arange(max_len, dtype=jnp.float32)[:, None] * inv_freq[None, :]


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """Rotate [B, S, H, D] by per-position angles [S, D/2] or [B, S, D/2].

    Split-halves (rotate_half) convention: x[i] pairs with x[i + D/2] —
    NOT the interleaved (x[2i], x[2i+1]) layout original-LLaMA checkpoints
    use; porting such weights requires a one-time head-dim permutation.
    Elementwise VPU work that XLA fuses into the adjacent projection.
    Rotation happens in f32 (small-angle differences vanish in bf16) and
    returns in the input dtype for the MXU contraction that follows.
    """
    cos = jnp.cos(angles)[..., None, :]  # [..., S, 1, D/2]
    sin = jnp.sin(angles)[..., None, :]
    if angles.ndim == 2:  # [S, D/2] -> broadcast over batch
        cos, sin = cos[None], sin[None]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate((x1 * cos - x2 * sin, x1 * sin + x2 * cos), axis=-1)
    return out.astype(x.dtype)


# ------------------------------------------------------------------ modules
class GqaAttention(nn.Module):
    """Grouped-query attention with rotary embeddings."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, angles):
        cfg = self.cfg
        dense = functools.partial(
            nn.DenseGeneral, dtype=cfg.dtype, use_bias=False
        )
        q = dense(features=(cfg.n_heads, cfg.head_dim), name="wq")(x)
        # fused K/V: one [E, 2*KV*D] MXU matmul -> [B, S, 2, KV, D]
        kv = dense(features=(2, cfg.n_kv_heads, cfg.head_dim), name="wkv")(x)
        k, v = kv[:, :, 0], kv[:, :, 1]
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)
        attn = cfg.attention_fn or _einsum_attention
        if cfg.q_per_kv > 1 and not getattr(attn, "supports_gqa", False):
            # backend wants equal head counts: share each kv head across
            # its query group by broadcast (XLA fuses it into the score
            # contraction). GQA-native backends (pallas flash) instead
            # index the shared head inside the kernel — no repeat.
            k = jnp.repeat(k, cfg.q_per_kv, axis=2)
            v = jnp.repeat(v, cfg.q_per_kv, axis=2)
        out = attn(q, k, v, True)
        return dense(
            features=cfg.d_model, axis=(-2, -1), name="out"
        )(out)


def _einsum_attention(q, k, v, causal: bool) -> jax.Array:
    from tf_operator_tpu.models.transformer import dot_product_attention

    return dot_product_attention(q, k, v, causal)


class SwiGlu(nn.Module):
    """silu(x W_gate) * (x W_up) -> W_down, gate+up fused as [2, F]."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.DenseGeneral(
            features=(2, cfg.d_ff), dtype=cfg.dtype, use_bias=False, name="wi"
        )(x)
        h = nn.silu(h[..., 0, :]) * h[..., 1, :]
        return nn.Dense(
            cfg.d_model, dtype=cfg.dtype, use_bias=False, name="wo"
        )(h)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, angles):
        cfg = self.cfg
        norm = functools.partial(
            nn.RMSNorm, epsilon=cfg.norm_eps, dtype=cfg.dtype
        )
        x = x + GqaAttention(cfg, name="attn")(norm(name="ln1")(x), angles)
        return x + SwiGlu(cfg, name="mlp")(norm(name="ln2")(x))


class Llama(nn.Module):
    """Causal decoder LM; same call contract as models/transformer.py
    Transformer (tokens -> f32 logits; `return_hidden` for blocked CE;
    `positions` for permuted token layouts)."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, train: bool = True, return_hidden: bool = False,
                 positions=None):
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="embed"
        )
        table = rope_table(cfg.max_len, cfg.head_dim, cfg.rope_theta)
        if positions is None:
            angles = table[: tokens.shape[1]]  # [S, D/2]
        else:
            angles = table[positions]  # [S, D/2] or [B, S, D/2]
        x = embed(tokens)
        block = nn.remat(LlamaBlock) if cfg.remat else LlamaBlock
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"block{i}")(x, angles)
        x = nn.RMSNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype, name="ln_f")(x)
        if return_hidden:
            return x
        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(
                cfg.vocab_size, dtype=jnp.float32, use_bias=False,
                name="lm_head",
            )(x)
        return logits.astype(jnp.float32)


def params_flops_per_token(cfg: LlamaConfig) -> float:
    """~6 * matmul-params FLOPs/token for a train step (fwd+bwd)."""
    attn = (cfg.n_heads + 2 * cfg.n_kv_heads + cfg.n_heads) * (
        cfg.d_model * cfg.head_dim
    )
    mlp = 3 * cfg.d_model * cfg.d_ff
    p = cfg.vocab_size * cfg.d_model + cfg.n_layers * (attn + mlp)
    return 6.0 * p
